"""Batched gradient-descent calibration against a target rollout.

The deliverable of the differentiable subsystem: fit an EOS gamma and/or
an initial-condition amplitude to a target Sedov (or any namelist)
profile by Adam descent through the checkpointed adjoint rollout.  B
independent members — each its own parameter guess — advance in ONE
compiled program (``vmap(value_and_grad(member_loss))``), the inverse
analog of the forward ensemble engine (``ensemble/batch.py``).

Service shape mirrors the run service:

* ``&CALIBRATION_PARAMS`` namelist block (config.CalibrationParams),
  ``__main__ --calibrate`` and ``calibrate``-kind jobs through
  ``ensemble/queue.py`` + ``service.py`` all land in
  :func:`run_calibration_job`;
* optimizer-state checkpoints are manifest-valid ``output_NNNNN`` dirs
  (``resilience/checkpoint.py``), so ``auto_resume`` restarts a killed
  calibration from the last finalized iterate — the deterministic
  ``fault_inject`` sigterm@K harness exercises exactly that in CI;
* diverged members (non-finite or runaway loss) are quarantined via the
  BatchGuard ladder — parameters and Adam moments freeze, the batch
  keeps running, telemetry records the eviction;
* per-iteration loss curve / gradient norm / step time land in telemetry
  ``calibrate_iter`` records.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.config import Params
from ramses_tpu.diff import optim
from ramses_tpu.diff.rollout import rollout_loss
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.grid.uniform import UniformGrid, run_steps
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.init.regions import condinit

CKPT_NPZ = "calibration.npz"
CKPT_JSON = "calibration.json"


def build_problem(params: Params, dtype):
    """(grid, u0, tend) for the calibration rollout — the same
    resolution/IC construction as the uniform driver (driver.Simulation)."""
    cfg = HydroStatic.from_params(params)
    lmin = params.amr.levelmin
    n = 2 ** lmin
    base = [params.amr.nx, params.amr.ny, params.amr.nz][:params.ndim]
    shape = tuple(b * n for b in base)
    dx = params.amr.boxlen / n
    grid = UniformGrid(cfg=cfg, shape=shape, dx=dx,
                       bc=bmod.BoundarySpec.from_params(params))
    u0 = jnp.asarray(condinit(shape, dx, params, cfg), dtype)
    tend = float(params.calibration.tend)
    if tend <= 0.0:
        touts = params.output.tout[:params.output.noutput]
        if not touts:
            raise ValueError("calibration needs &CALIBRATION_PARAMS tend "
                             "or an &OUTPUT_PARAMS tout ladder")
        tend = float(touts[-1])
    return grid, u0, tend


def make_target(grid: UniformGrid, u0, tend: float, nsteps: int):
    """The 'observation': a plain (undifferentiated) driver rollout at
    the namelist's true parameters."""
    t0 = jnp.zeros((), u0.dtype)
    u, _, _ = run_steps(grid, u0, t0, jnp.asarray(tend, u0.dtype), nsteps)
    return u


def _init_theta(cal, truth_gamma: float, B: int, dtype):
    """Per-member initial parameter guesses ``{name: [B]}``."""
    th = {}
    if cal.fit_gamma:
        g0 = (float(cal.gamma_guess) if cal.gamma_guess > 0.0
              else truth_gamma * (1.0 + float(cal.guess_spread)))
        if B > 1:
            # half-width spread around the guess so no member starts on
            # the truth by construction (g0 - spread/2 > truth)
            off = (np.linspace(-0.5, 0.5, B)
                   * float(cal.guess_spread) * truth_gamma)
            g = g0 + off
        else:
            g = np.full((1,), g0)
        th["gamma"] = jnp.asarray(g, dtype)
    if cal.fit_ic:
        th["ic_logamp"] = jnp.full((B,), float(cal.ic_guess), dtype)
    if not th:
        raise ValueError("&CALIBRATION_PARAMS: nothing to fit "
                         "(fit_gamma and fit_ic both off)")
    return th


def _member_loss_fn(grid, u0, target, tend, nsteps, inner):
    t0 = jnp.zeros((), u0.dtype)
    tend = jnp.asarray(tend, u0.dtype)

    def member_loss(th):
        theta = {}
        if "ic_logamp" in th:
            theta["ic_scale"] = jnp.exp(th["ic_logamp"])
        if "gamma" in th:
            theta["gamma"] = th["gamma"]
        return rollout_loss(theta, u0, target, grid, t0, tend, nsteps,
                            inner=inner)

    return member_loss


def _make_update(member_loss, lr: float, grad_clip: float):
    @jax.jit
    def update(theta, ostate, active):
        loss, grads = jax.vmap(jax.value_and_grad(member_loss))(theta)
        # zero quarantined members' gradients FIRST so a frozen-NaN
        # member cannot poison the clip scale or the Adam moments
        grads = jax.tree_util.tree_map(
            lambda g: jnp.where(active, g, 0.0), grads)
        if grad_clip > 0.0:
            grads, gnorm = optim.clip_by_global_norm(grads, grad_clip,
                                                     axis=0)
        else:
            gnorm = optim.global_norm(grads, axis=0)
        theta2, ostate2 = optim.adam_update(grads, ostate, theta, lr=lr)
        sel = lambda new, old: jnp.where(active, new, old)  # noqa: E731
        theta2 = jax.tree_util.tree_map(sel, theta2, theta)
        ostate2 = optim.AdamState(
            m=jax.tree_util.tree_map(sel, ostate2.m, ostate.m),
            v=jax.tree_util.tree_map(sel, ostate2.v, ostate.v),
            count=ostate2.count)
        return loss, gnorm, theta2, ostate2

    return update


def _save_checkpoint(base_dir: str, it: int, theta, ostate, active,
                     hist, spec, keep: int = 2) -> str:
    """Optimizer-state checkpoint as a manifest-valid output_NNNNN dir
    (stage → manifest → atomic rename), resumable by auto_resume."""
    from ramses_tpu.resilience.checkpoint import (finalize_checkpoint,
                                                  rotate_checkpoints)
    stage = os.path.join(base_dir, f"output_{it:05d}.stage")
    os.makedirs(stage, exist_ok=True)
    flat = {"active": np.asarray(active),
            "count": np.asarray(ostate.count),
            "loss_hist": np.asarray(hist, dtype=np.float64)}
    for k, v in theta.items():
        flat[f"theta_{k}"] = np.asarray(v)
        flat[f"m_{k}"] = np.asarray(ostate.m[k])
        flat[f"v_{k}"] = np.asarray(ostate.v[k])
    np.savez(os.path.join(stage, CKPT_NPZ), **flat)
    with open(os.path.join(stage, CKPT_JSON), "w") as f:
        json.dump(dict(spec, iter=it), f)
    final = finalize_checkpoint(
        stage, os.path.join(base_dir, f"output_{it:05d}"),
        {"kind": "calibrate", "nstep": it, "t": float(it), "iout": it})
    if keep:
        rotate_checkpoints(base_dir, keep)
    return final


def _load_checkpoint(path: str, spec, dtype, log):
    """Restore (start_iter, theta, ostate, active, hist) from a
    finalized calibration checkpoint; None on any spec mismatch (a
    changed problem must not silently continue a stale optimize)."""
    npz_path = os.path.join(path, CKPT_NPZ)
    json_path = os.path.join(path, CKPT_JSON)
    if not (os.path.isfile(npz_path) and os.path.isfile(json_path)):
        return None
    with open(json_path) as f:
        saved = json.load(f)
    it = int(saved.pop("iter", 0))
    if {k: saved.get(k) for k in spec} != dict(spec):
        if log:
            log(f"calibrate: checkpoint {path} was written for a "
                "different problem spec; starting fresh")
        return None
    data = np.load(npz_path)
    names = [k[len("theta_"):] for k in data.files
             if k.startswith("theta_")]
    theta = {k: jnp.asarray(data[f"theta_{k}"], dtype) for k in names}
    ostate = optim.AdamState(
        m={k: jnp.asarray(data[f"m_{k}"], dtype) for k in names},
        v={k: jnp.asarray(data[f"v_{k}"], dtype) for k in names},
        count=jnp.asarray(data["count"]))
    active = np.asarray(data["active"]).astype(bool)
    hist = list(np.asarray(data["loss_hist"]))
    return it, theta, ostate, active, hist


def run_calibration_job(params: Params, dtype=None,
                        base_dir: Optional[str] = None,
                        log: Optional[Callable] = print,
                        on_iter: Optional[Callable] = None) -> dict:
    """Run (or resume) one calibration described by a namelist.

    Returns a result dict with the recovered parameters, loss history
    endpoints, quarantine census and the last checkpoint path.
    ``on_iter(it, loss[B])`` fires once per optimizer iteration — the
    queue service uses it to heartbeat the job record.
    """
    from ramses_tpu.resilience.checkpoint import resolve_restart_dir
    from ramses_tpu.resilience.faultinject import FaultInjector
    from ramses_tpu.resilience.stepguard import BatchGuard
    from ramses_tpu.telemetry.recorder import make_telemetry

    cal = params.calibration
    if dtype is None:
        dtype = (jnp.float64 if jax.config.jax_enable_x64
                 else jnp.float32)
    base_dir = base_dir if base_dir is not None else "."
    os.makedirs(base_dir, exist_ok=True)

    grid, u0, tend = build_problem(params, dtype)
    nsteps = int(cal.nsteps)
    inner = int(cal.inner) or None
    niter = int(cal.niter)
    truth = float(grid.cfg.gamma)
    B = max(1, int(cal.nmember))
    spec = {"niter": niter, "nmember": B, "nsteps": nsteps,
            "fit_gamma": bool(cal.fit_gamma), "fit_ic": bool(cal.fit_ic),
            "gamma_truth": truth, "tend": tend}

    target = make_target(grid, u0, tend, nsteps)
    member_loss = _member_loss_fn(grid, u0, target, tend, nsteps, inner)
    update = _make_update(member_loss, float(cal.lr),
                          float(cal.grad_clip))

    theta = _init_theta(cal, truth, B, dtype)
    ostate = optim.adam_init(theta)
    active = np.ones(B, dtype=bool)
    hist: list = []
    start_iter = 0
    resumed_from = None
    rdir = resolve_restart_dir(params, base_dir, log=log)
    if rdir is not None:
        loaded = _load_checkpoint(rdir, spec, dtype, log)
        if loaded is not None:
            start_iter, theta, ostate, active, hist = loaded
            resumed_from = start_iter
            if log:
                log(f"calibrate: resumed optimizer state at iteration "
                    f"{start_iter} from {rdir}")

    telemetry = make_telemetry(params, run_info={
        "driver": "Calibration", "nmember": B, "niter": niter})
    guard = BatchGuard(max_retries=0, telemetry=telemetry)
    injector = FaultInjector.from_params(params)
    ckpt_every = int(cal.checkpoint_every)
    last_ckpt = rdir
    loss_h = np.full(B, np.nan)

    for it in range(start_iter, niter):
        if injector is not None:
            injector.maybe_signal(it)
        tic = time.perf_counter()
        loss, gnorm, theta, ostate = update(theta, ostate,
                                            jnp.asarray(active))
        loss_h = np.asarray(loss)
        gnorm_h = np.asarray(gnorm)
        dt_it = time.perf_counter() - tic

        bad = ~np.isfinite(loss_h) | ~np.isfinite(gnorm_h)
        if float(cal.diverge_loss) > 0.0:
            bad |= loss_h > float(cal.diverge_loss)
        newly = bad & active
        if newly.any():
            guard.trips += int(newly.sum())
            for m in np.nonzero(newly)[0]:
                guard.record_quarantine(int(m), {
                    "reason": "diverged", "nstep": it,
                    "t": float(loss_h[m])
                    if np.isfinite(loss_h[m]) else -1.0})
            active &= ~bad
        live = loss_h[active] if active.any() else loss_h
        hist.append(float(np.min(live)))
        telemetry.record_event(
            "calibrate_iter", iter=it,
            loss_min=float(np.min(live)), loss_mean=float(np.mean(live)),
            grad_norm_max=float(np.max(gnorm_h[active]))
            if active.any() else float("nan"),
            step_time_s=dt_it, active=int(active.sum()))
        if on_iter is not None:
            on_iter(it, loss_h)
        if ckpt_every and (it + 1) % ckpt_every == 0:
            last_ckpt = _save_checkpoint(base_dir, it + 1, theta, ostate,
                                         active, hist, spec)

    last_ckpt = _save_checkpoint(base_dir, niter, theta, ostate, active,
                                 hist, spec)
    result = {
        "iterations": niter,
        "start_iter": start_iter,
        "resumed_from": resumed_from,
        "nmember": B,
        "active": int(active.sum()),
        "quarantined": int(B - int(active.sum())),
        "loss_first": (hist[0] if hist else None),
        "loss_final": (hist[-1] if hist else None),
        "gamma_truth": truth,
        "checkpoint": last_ckpt,
    }
    if "gamma" in theta:
        g = np.asarray(theta["gamma"])
        result["gamma"] = [float(x) for x in g]
        # best member = lowest final loss among the live ones (truth is
        # unknown in a real calibration)
        score = np.where(active & np.isfinite(loss_h), loss_h, np.inf)
        if np.isfinite(score).any():
            result["gamma_best"] = float(g[int(np.argmin(score))])
    if "ic_logamp" in theta:
        result["ic_logamp"] = [float(x)
                               for x in np.asarray(theta["ic_logamp"])]
    telemetry.record_event("calibrate_done", **{
        k: v for k, v in result.items()
        if isinstance(v, (int, float, str)) and v is not None})
    telemetry.close()
    if log:
        msg = (f"calibrate: {niter - start_iter} iterations, loss "
               f"{result['loss_first']} -> {result['loss_final']}")
        if "gamma" in result:
            msg += (f", gamma {result['gamma']} (truth {truth})")
        log(msg)
    return result
