"""Differentiable solver subsystem (ROADMAP item 4).

Gradient-safe step chains (:mod:`ramses_tpu.diff.rollout`), an in-repo
Adam optimizer (:mod:`ramses_tpu.diff.optim`) and a batched calibration
service (:mod:`ramses_tpu.diff.calibrate`).  Nothing in the
undifferentiated drivers imports this package — the adjoint path is
strictly opt-in (pinned by ``tests/test_diff.py``).
"""

from ramses_tpu.diff.rollout import (checkpointed_run_steps, default_inner,
                                     rollout, rollout_loss, rollout_mhd)
from ramses_tpu.diff.optim import (AdamState, adam_init, adam_update,
                                   clip_by_global_norm, global_norm)

__all__ = [
    "checkpointed_run_steps", "default_inner", "rollout", "rollout_loss",
    "rollout_mhd", "AdamState", "adam_init", "adam_update",
    "clip_by_global_norm", "global_norm",
]
