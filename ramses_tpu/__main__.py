"""Command-line entry point: ``python -m ramses_tpu run.nml``.

The ``program ramses`` equivalent (``amr/ramses.f90:1-15``): parse the
namelist given as first argument, run the adaptive loop, write snapshots
at the configured output times.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ramses_tpu",
        description="TPU-native AMR astrophysics framework")
    ap.add_argument("namelist", nargs="?", default=None,
                    help="Fortran-namelist runtime config (optional "
                         "with --serve)")
    ap.add_argument("--serve", metavar="QUEUE_DIR", default=None,
                    help="run-service worker: claim jobs from this "
                         "queue dir and run them under the supervised "
                         "ensemble engine (ramses_tpu/ensemble); "
                         "SIGTERM drains gracefully — finish the "
                         "chunk, checkpoint, requeue held jobs with "
                         "stage=drain, exit 0")
    ap.add_argument("--submit", metavar="QUEUE_DIR", default=None,
                    help="enqueue the namelist as a job instead of "
                         "running it; prints the job id")
    ap.add_argument("--calibrate", action="store_true",
                    help="run (or with --submit, enqueue) the namelist "
                         "as a gradient-descent calibration against a "
                         "target rollout (&CALIBRATION_PARAMS, "
                         "ramses_tpu/diff) instead of a forward "
                         "simulation")
    ap.add_argument("--sweep", action="append", metavar="KEY=V1,V2,...",
                    help="with --submit: per-member parameter sweep "
                         "rows, dotted paths into the namelist "
                         "(e.g. init.p_region[1]=0.3,0.5); repeatable")
    ap.add_argument("--max-jobs", type=int, default=0,
                    help="with --serve: stop after this many jobs "
                         "(0 = keep serving)")
    ap.add_argument("--idle-exit", action="store_true",
                    help="with --serve: exit once the queue is drained "
                         "instead of polling")
    ap.add_argument("--stale-timeout", type=float, default=300.0,
                    help="with --serve: reclaim running jobs whose "
                         "heartbeat is older than this many seconds")
    ap.add_argument("--worker-id", default="",
                    help="with --serve: worker name stamped on claimed "
                         "jobs (default host:pid)")
    ap.add_argument("--obs", metavar="QUEUE_DIR", default=None,
                    help="standalone observability server: serve the "
                         "streaming results API + Prometheus /metrics "
                         "over this queue dir (ramses_tpu/obs) without "
                         "running any jobs; Ctrl-C to stop")
    ap.add_argument("--obs-port", type=int, default=None,
                    help="with --serve or --obs: TCP port for the "
                         "observability HTTP server (0 = pick an "
                         "ephemeral port; default with --obs: 9100, "
                         "with --serve: off)")
    ap.add_argument("--obs-bind", default="127.0.0.1",
                    help="bind address for the observability server "
                         "(default loopback; 0.0.0.0 exposes it)")
    ap.add_argument("--claim-order", default="cost",
                    choices=["cost", "fifo"],
                    help="with --serve: job claim order — 'cost' "
                         "(default) gang-schedules by the submit-time "
                         "cost stamp to fill the local device mesh, "
                         "'fifo' restores blind oldest-first claiming")
    ap.add_argument("--ndim", type=int, default=3,
                    help="spatial dimensions (compile-time in the reference)")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "float64", "bfloat16"])
    ap.add_argument("--amr", action="store_true",
                    help="force the multi-level AMR driver even when "
                         "levelmin==levelmax")
    ap.add_argument("--solver", default=None,
                    choices=["hydro", "mhd", "rhd"],
                    help="solver family (the reference's SOLVER= make "
                         "variable); default: mhd when &INIT_PARAMS sets "
                         "A/B/C_region, hydro otherwise")
    ap.add_argument("--patch", default=None,
                    help="user plug-in file overriding condinit/gravana/"
                         "boundana/source hooks (the runtime equivalent "
                         "of the reference's compile-time PATCH= VPATH "
                         "shadowing, bin/Makefile:153-160)")
    ap.add_argument("--verbose", "-v", action="store_true")
    ap.add_argument("--walltime", type=float, default=None,
                    help="wall-clock budget in hours; the watchdog dumps "
                         "a restartable snapshot and stops before it "
                         "expires (amr/adaptive_loop.f90:216-226)")
    ap.add_argument("--auto-resume", action="store_true",
                    help="resume from the newest manifest-valid "
                         "checkpoint in the output dir (same as "
                         "&RUN_PARAMS auto_resume=.true.)")
    ap.add_argument("--max-attempts", type=int, default=1,
                    help="supervised retry-with-resume: on an "
                         "interrupted or failed run, rebuild from the "
                         "latest valid checkpoint and continue, up to "
                         "this many attempts (exponential backoff)")
    args = ap.parse_args(argv)

    # run-service front-end: --submit enqueues and exits; --serve is
    # the worker loop (no namelist needed — jobs carry their own)
    if args.submit:
        if not args.namelist:
            ap.error("--submit requires a namelist")
        from ramses_tpu.ensemble.service import (parse_sweep_args,
                                                 submit_namelist)
        job_id = submit_namelist(
            args.submit, args.namelist,
            sweeps=parse_sweep_args(args.sweep),
            solver=args.solver or "", ndim=args.ndim, dtype=args.dtype,
            kind="calibrate" if args.calibrate else "run")
        print(job_id)
        return 0
    if args.obs:
        # artifacts-only observability: no jobs run, no devices touched
        # — consumers hit the queue dir's records/telemetry/checkpoints
        import time as _time

        from ramses_tpu.obs.server import ObsServer
        port = 9100 if args.obs_port is None else args.obs_port
        srv = ObsServer(args.obs, port=port, bind=args.obs_bind,
                        log=print if args.verbose else None).start()
        print(f"obs: serving {srv.root} on {srv.url} (Ctrl-C to stop)",
              flush=True)
        try:
            while True:
                _time.sleep(3600.0)
        except KeyboardInterrupt:
            pass
        finally:
            srv.close()
        return 0
    if args.serve:
        from ramses_tpu.ensemble.service import serve
        counts = serve(args.serve, worker=args.worker_id,
                       max_jobs=args.max_jobs, idle_exit=args.idle_exit,
                       stale_s=args.stale_timeout,
                       max_attempts=max(1, args.max_attempts),
                       verbose=args.verbose, order=args.claim_order,
                       obs_port=args.obs_port, obs_bind=args.obs_bind)
        print(f"serve: done={counts['done']} failed={counts['failed']}")
        return 1 if counts["failed"] else 0
    if not args.namelist:
        ap.error("a namelist is required (or use --serve/--submit)")

    import jax.numpy as jnp

    from ramses_tpu.config import load_params

    dtype = getattr(jnp, args.dtype)
    params = load_params(args.namelist, ndim=args.ndim)

    # persistent compile cache (&RUN_PARAMS compile_cache_dir, env
    # RAMSES_COMPILE_CACHE): must land before the first trace
    from ramses_tpu.platform import setup_compile_cache
    setup_compile_cache(params)

    # &OUTPUT_PARAMS obs_port: a solo run serves its own output dir
    # over HTTP as pseudo-job "run" — telemetry tail + artifact files,
    # same endpoints as the fleet server (daemon thread, dies with the
    # process)
    if params.output.obs_port:
        import os as _os

        from ramses_tpu.obs.server import ObsServer
        _os.makedirs(params.output.output_dir, exist_ok=True)
        obs_srv = ObsServer(params.output.output_dir,
                            port=params.output.obs_port,
                            bind=params.output.obs_bind).start()
        print(f"obs: serving {params.output.output_dir} "
              f"on {obs_srv.url}")

    if params.run.debug_nan:
        # jit-level NaN trap (SURVEY.md §5.2): every compiled program
        # re-checks outputs and raises AT the producing op — the
        # runtime analogue of the reference's FPE-trapping debug build
        import jax
        jax.config.update("jax_debug_nans", True)

    if args.patch:
        from ramses_tpu import patch
        patch.install(args.patch, verbose=True)

    solver = args.solver
    if solver is None:
        solver = ("mhd" if any(params.init.A_region) or
                  any(params.init.B_region) or any(params.init.C_region)
                  else "hydro")

    def make_guard(sim):
        from ramses_tpu.utils.ops import OpsGuard
        return OpsGuard(sim, params.output.output_dir,
                        walltime_s=(args.walltime * 3600.0
                                    if args.walltime else None))

    # Supervised retry-with-resume (ramses_tpu/resilience): every branch
    # is phrased as build(restart_dir)/drive(sim) and routed through the
    # supervisor, which resolves nrestart/auto_resume on attempt 1 and
    # rebuilds from the newest manifest-valid checkpoint on later ones.
    if args.auto_resume:
        params.run.auto_resume = True

    # --calibrate (or &CALIBRATION_PARAMS calibrate=.true.): the
    # namelist describes an *inverse* problem — fit IC/EOS parameters
    # to a target rollout by gradient descent through the
    # differentiable step chain (ramses_tpu/diff), resumable from
    # optimizer-state checkpoints like any forward run
    if args.calibrate or params.calibration.calibrate:
        from ramses_tpu.diff.calibrate import run_calibration_job
        res = run_calibration_job(params, dtype=dtype,
                                  base_dir=params.output.output_dir)
        best = (f"gamma_best={res['gamma_best']:.6g} "
                if "gamma_best" in res else "")
        print(f"calibrate: {res['iterations']} iters "
              f"(resumed at {res['start_iter']}) "
              f"nmember={res['nmember']} "
              f"quarantined={res['quarantined']} "
              f"loss {res['loss_first']:.4e} -> "
              f"{res['loss_final']:.4e} "
              f"{best}-> {res['checkpoint']}")
        return 0

    supervised = (args.max_attempts > 1 or params.run.auto_resume
                  or params.run.nrestart == -1)
    attempts = max(2, args.max_attempts) if supervised else 1

    def launch(build, drive, tend=None):
        from ramses_tpu.resilience import supervisor as rsup
        return rsup.supervise(build, drive, params,
                              base_dir=params.output.output_dir,
                              max_attempts=attempts, tend=tend)

    # &ENSEMBLE_PARAMS nmember > 1: the whole namelist is an ensemble —
    # one compiled program advances every member (ramses_tpu/ensemble)
    if params.ensemble.nmember > 1:
        from ramses_tpu.ensemble.batch import EnsembleEngine, EnsembleSpec
        spec = EnsembleSpec.from_params(params, solver=args.solver or "")

        def build(restart):
            if restart:
                return EnsembleEngine.from_checkpoint(spec, restart,
                                                      dtype=dtype)
            return EnsembleEngine(spec, dtype=dtype)

        eng = launch(build, lambda e: e.run(verbose=args.verbose))
        snap = eng.save(params.output.output_dir)
        print(f"ensemble: {eng.nmember} members "
              f"{len(eng.groups)} compile groups t_min={eng.t:.5e} "
              f"nstep_max={eng.nstep} "
              f"quarantined={eng.quarantined_count} -> {snap}")
        for k, info in sorted(eng.quarantined.items()):
            print(f"ensemble: member {k} quarantined: "
                  f"{info.get('reason')} at nstep={info.get('nstep')} "
                  f"t={info.get('t')}")
        eng.telemetry.close(eng)
        return 0

    def drive_amr(tend):
        def drive(sim):
            guard = make_guard(sim)
            guard.run_guarded(lambda: sim.evolve(
                tend, nstepmax=params.run.nstepmax,
                verbose=args.verbose, guard=guard))
        return drive

    if solver == "rhd":
        if args.amr or params.amr.levelmax > params.amr.levelmin:
            from ramses_tpu.rhd.amr import RhdAmrSim
            tend = (params.output.tout[-1] if params.output.tout
                    else params.output.tend)
            sim = launch(
                lambda restart: (
                    RhdAmrSim.from_checkpoint_dir(params, restart,
                                                  dtype=dtype)
                    if restart else RhdAmrSim(params, dtype=dtype)),
                drive_amr(tend), tend=tend)
            print(f"rhd-amr t={sim.t:.5e} nstep={sim.nstep} "
                  f"lor_max={sim.max_lorentz():.3f} "
                  f"octs={[sim.tree.noct(l) for l in sim.levels()]}")
            sim.dump(1, params.output.output_dir,
                     namelist_path=args.namelist)
        else:
            from ramses_tpu.rhd.driver import RhdSimulation

            def drive(sim):
                guard = make_guard(sim)
                guard.run_guarded(lambda: sim.evolve(
                    nstepmax=params.run.nstepmax, verbose=args.verbose,
                    guard=guard))

            sim = launch(
                lambda restart: (
                    RhdSimulation.from_snapshot(params, restart,
                                                dtype=dtype)
                    if restart else RhdSimulation(params, dtype=dtype)),
                drive)
            sim.dump(1, params.output.output_dir,
                     namelist_path=args.namelist)
    elif solver == "mhd":
        if args.amr or params.amr.levelmax > params.amr.levelmin:
            from ramses_tpu.mhd.amr import MhdAmrSim
            tend = (params.output.tout[-1] if params.output.tout
                    else params.output.tend)
            sim = launch(
                lambda restart: (
                    MhdAmrSim.from_checkpoint_dir(params, restart,
                                                  dtype=dtype)
                    if restart else MhdAmrSim(params, dtype=dtype)),
                drive_amr(tend), tend=tend)
            print(f"mhd-amr t={sim.t:.5e} nstep={sim.nstep} "
                  f"max|divB|/max|B|*dx={sim.max_divb():.3e}")
            sim.dump(1, params.output.output_dir,
                     namelist_path=args.namelist)
        else:
            from ramses_tpu.mhd.driver import MhdSimulation

            def drive(sim):
                guard = make_guard(sim)
                guard.run_guarded(lambda: sim.evolve(
                    nstepmax=params.run.nstepmax, verbose=args.verbose,
                    guard=guard))

            sim = launch(
                lambda restart: (
                    MhdSimulation.from_snapshot(params, restart,
                                                dtype=dtype)
                    if restart else MhdSimulation(params, dtype=dtype)),
                drive)
            sim.dump(1, params.output.output_dir,
                     namelist_path=args.namelist)
    elif args.amr or params.amr.levelmax > params.amr.levelmin:
        from ramses_tpu.amr.hierarchy import AmrSim

        def build(restart):
            if restart:
                return AmrSim.from_checkpoint_dir(params, restart,
                                                  dtype=dtype)
            particles = None
            dense = None
            if (params.run.cosmo and params.init.initfile
                    and params.init.filetype in ("grafic", "gadget")):
                from ramses_tpu.driver import load_cosmo_ics
                from ramses_tpu.hydro.core import HydroStatic
                from ramses_tpu.pm.cosmology import Cosmology
                cosmo = Cosmology.from_params(params)
                n = 2 ** params.amr.levelmin
                particles, dense = load_cosmo_ics(
                    params, cosmo, HydroStatic.from_params(params),
                    (n,) * params.ndim)
            return AmrSim(params, dtype=dtype, particles=particles,
                          init_dense_u=dense)

        def amr_tend(sim):
            if sim.cosmo is not None and params.output.aout:
                return float(sim.cosmo.tau_of_aexp(
                    min(params.output.aout[-1], 1.0)))
            return (params.output.tout[-1] if params.output.tout
                    else params.output.tend)

        def drive(sim):
            guard = make_guard(sim)
            guard.run_guarded(lambda: sim.evolve(
                amr_tend(sim), nstepmax=params.run.nstepmax,
                verbose=args.verbose, guard=guard))

        sim = launch(build, drive)
        if sim.cosmo is not None:
            print(f"cosmo-amr aexp={sim.aexp_now():.4f} nstep={sim.nstep} "
                  f"octs={[sim.tree.noct(l) for l in sim.levels()]}")
        sim.dump(1, params.output.output_dir, namelist_path=args.namelist)
    else:
        from ramses_tpu.driver import Simulation

        def build(restart):
            sim = (Simulation.from_snapshot(params, restart, dtype=dtype)
                   if restart else Simulation(params, dtype=dtype))
            sim.on_output = lambda s, i: s.dump(
                i, namelist_path=args.namelist)
            return sim

        def drive(sim):
            guard = make_guard(sim)
            guard.run_guarded(lambda: sim.evolve(verbose=args.verbose,
                                                 guard=guard))

        sim = launch(build, drive)
    # run-footer + output_timer breakdown (telemetry also closes via
    # atexit, but a clean exit should flush before the interpreter
    # teardown races the JSONL file handle)
    tel = getattr(sim, "telemetry", None)
    if tel is not None:
        tel.close(sim)
    return 0


if __name__ == "__main__":
    from ramses_tpu.resilience.watchdog import (HANG_EXIT_CODE,
                                                HangDetected)
    try:
        sys.exit(main())
    except HangDetected as e:
        # hang budget exhausted: exit with the dedicated status so a
        # parent (batch system, bench subprocess capture) classifies
        # hang vs crash without parsing logs
        print(f"ramses_tpu: unrecoverable hang: {e}", file=sys.stderr)
        sys.exit(HANG_EXIT_CODE)
