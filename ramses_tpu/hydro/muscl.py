"""Unsplit second-order MUSCL-Hancock Godunov integrator.

TPU-native re-design of the reference kernel pipeline
``ctoprim → uslope → trace{1,2,3}d → cmpflxm → riemann_*``
(``hydro/umuscl.f90:22-171,861-1480``).  The Fortran operates on
``nvector``-batched 6^ndim oct stencils; here every function is a pure op
on whole (ghost-padded) grids of shape ``[nvar, *spatial]`` — the level
batch IS the array, XLA fuses the pipeline, and the same code serves the
uniform-grid solver and the per-oct AMR batches (where the leading spatial
axes are the oct batch).

Ghost-cell contract: callers pad with ``NGHOST=2`` cells per side (the
active-face update consumes exactly two upwind cells, matching the
reference's 6-wide stencil for a 2-wide oct).  Shifted neighbours are taken
with ``jnp.roll``; wrap-around touches only ghost results that the active
region never consumes.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ramses_tpu.hydro import riemann as rsolve
from ramses_tpu.hydro.core import HydroStatic

NGHOST = 2


def _axis(cfg: HydroStatic, d: int, u) -> int:
    """Spatial axis of direction d: trailing spatial axes by default, or
    axes 1..ndim when ``cfg.trailing_batch`` ([nvar, *spatial, batch])."""
    if getattr(cfg, "trailing_batch", False):
        return 1 + d
    return u.ndim - cfg.ndim + d


def ctoprim(u, grav, dt, cfg: HydroStatic):
    """Conservative → primitive + sound speed + gravity predictor.

    (``hydro/umuscl.f90:861-967``.)  ``grav`` may be None (no gravity).
    Returns (q, c) with q in primitive layout (core.py docstring).
    """
    r = jnp.maximum(u[0], cfg.smallr)
    inv_r = 1.0 / r
    vels = [u[1 + d] * inv_r for d in range(cfg.ndim)]
    eken = sum(0.5 * v * v for v in vels)
    erad = jnp.zeros_like(r)
    prad = []
    for n in range(cfg.nener):
        prad.append((cfg.gamma_rad[n] - 1.0) * u[2 + cfg.ndim + n])
        erad = erad + u[2 + cfg.ndim + n] * inv_r
    eint = jnp.maximum(u[cfg.ndim + 1] * inv_r - eken - erad, cfg.smalle)
    p = (cfg.gamma - 1.0) * r * eint
    c2 = cfg.gamma * p
    for n in range(cfg.nener):
        c2 = c2 + cfg.gamma_rad[n] * prad[n]
    c = jnp.sqrt(c2 * inv_r)
    if grav is not None:
        vels = [v + g * (0.5 * dt) for v, g in zip(vels, grav)]
    comps = [r] + vels + [p] + prad
    for s in range(cfg.npassive):
        comps.append(u[2 + cfg.ndim + cfg.nener + s] * inv_r)
    return jnp.stack(comps), c


def uslope(q, cfg: HydroStatic, dt=None, dx=None):
    """TVD slopes per direction (``hydro/umuscl.f90:970-1393``).

    slope_type 0: zero | 1: minmod | 2: moncen | 7: van Leer |
    8: generalized minmod with ``slope_theta`` (van Leer 1979).
    Returns ``dq`` of shape ``[ndim, nvar, *spatial]``.
    """
    st = cfg.slope_type
    if st == 0:
        return jnp.zeros((cfg.ndim,) + q.shape, q.dtype)
    if st == 3 and cfg.ndim > 1:
        return _uslope_positivity(q, cfg)
    dq = []
    for d in range(cfg.ndim):
        ax = _axis(cfg, d, q)
        qm1 = jnp.roll(q, 1, axis=ax)
        qp1 = jnp.roll(q, -1, axis=ax)
        dlft = q - qm1
        drgt = qp1 - q
        if st in (1, 2, 3):
            f = float(min(st, 2))
            dcen = 0.5 * (dlft + drgt)
            slop = f * jnp.minimum(jnp.abs(dlft), jnp.abs(drgt))
            dlim = jnp.where(dlft * drgt <= 0.0, 0.0, slop)
            dq.append(jnp.sign(dcen) * jnp.minimum(dlim, jnp.abs(dcen)))
        elif st == 7:  # van Leer harmonic
            prod = dlft * drgt
            # Double-where: at an extremum dlft == -drgt makes the harmonic
            # mean 0/0-like; the where masks the forward value but reverse-
            # mode still multiplies the untaken branch's unbounded
            # derivative by a zero cotangent (inf * 0 = NaN).  Divide by a
            # guarded denominator instead — bit-identical where consumed.
            mono = prod > 0.0
            vl_den = jnp.where(mono, dlft + drgt + 1e-300, 1.0)
            vl = 2.0 * prod / vl_den
            dq.append(jnp.where(mono, vl, 0.0))
        elif st == 8:  # generalized moncen/minmod (theta)
            th = cfg.slope_theta
            dcen = 0.5 * (dlft + drgt)
            slop = th * jnp.minimum(jnp.abs(dlft), jnp.abs(drgt))
            dlim = jnp.where(dlft * drgt <= 0.0, 0.0, slop)
            dq.append(jnp.sign(dcen) * jnp.minimum(dlim, jnp.abs(dcen)))
        else:
            raise NotImplementedError(f"slope_type={st}")
    return jnp.stack(dq)


def _uslope_positivity(q, cfg: HydroStatic):
    """slope_type=3 positivity-preserving unsplit slopes for 2D/3D
    (``hydro/umuscl.f90`` 'positivity preserving {2d,3d} unsplit slope'
    branches): centred differences per direction, all scaled by one common
    limiter ``min(1, min(|vmin|,|vmax|)/dff)`` where vmin/vmax run over the
    3^ndim neighbourhood differences and ``dff = 0.5*sum_d |dcen_d|``."""
    import itertools
    nd = cfg.ndim
    axes = [_axis(cfg, d, q) for d in range(nd)]
    vmin = jnp.full_like(q, jnp.inf)
    vmax = jnp.full_like(q, -jnp.inf)
    for offs in itertools.product((-1, 0, 1), repeat=nd):
        qs = q
        for d, o in enumerate(offs):
            if o:
                qs = jnp.roll(qs, -o, axis=axes[d])
        df = qs - q
        vmin = jnp.minimum(vmin, df)
        vmax = jnp.maximum(vmax, df)
    dcen = [0.5 * (jnp.roll(q, -1, axis=axes[d]) - jnp.roll(q, 1, axis=axes[d]))
            for d in range(nd)]
    dff = 0.5 * sum(jnp.abs(dc) for dc in dcen)
    slop = jnp.where(dff > 0.0,
                     jnp.minimum(1.0, jnp.minimum(jnp.abs(vmin),
                                                  jnp.abs(vmax))
                                 / jnp.where(dff > 0.0, dff, 1.0)),
                     1.0)
    return jnp.stack([slop * dc for dc in dcen])


def trace(q, dq, dt, dx: Sequence[float], cfg: HydroStatic):
    """MUSCL-Hancock half-dt predictor (``hydro/umuscl.f90:176-714``,
    trace1d/2d/3d unified over ndim).

    Returns (qm, qp): per-direction left/right interface states, each of
    shape ``[ndim, nvar, *spatial]``.  ``qm[d]`` is the state at the cell's
    high-side (right) face, ``qp[d]`` at its low-side (left) face.
    """
    nd = cfg.ndim
    ip = nd + 1  # pressure index
    r = q[0]
    p = q[ip]
    vels = [q[1 + d] for d in range(nd)]
    dr = [dq[d][0] for d in range(nd)]
    dp = [dq[d][ip] for d in range(nd)]
    dv = [[dq[d][1 + j] for j in range(nd)] for d in range(nd)]  # dv[dir][comp]

    divv = sum(dv[d][d] for d in range(nd))
    sr0 = -sum(vels[d] * dr[d] for d in range(nd)) - divv * r
    sp0 = -sum(vels[d] * dp[d] for d in range(nd)) - divv * cfg.gamma * p
    sv0 = []
    for j in range(nd):
        s = -sum(vels[d] * dv[d][j] for d in range(nd)) - dp[j] / r
        for n in range(cfg.nener):
            s = s - dq[j][ip + 1 + n] / r
        sv0.append(s)
    se0 = []
    for n in range(cfg.nener):
        e = q[ip + 1 + n]
        se0.append(-sum(vels[d] * dq[d][ip + 1 + n] for d in range(nd))
                   - divv * cfg.gamma_rad[n] * e)
    sa0 = []
    for s in range(cfg.npassive):
        i = ip + 1 + cfg.nener + s
        sa0.append(-sum(vels[d] * dq[d][i] for d in range(nd)))

    qm, qp = [], []
    for d in range(nd):
        dtdx2 = 0.5 * dt / dx[d]
        half_d = 0.5 * dq[d]

        def build(sgn):
            comps = [None] * q.shape[0]
            rho = r + sgn * half_d[0] + sr0 * dtdx2
            comps[0] = jnp.where(rho < cfg.smallr, r, rho)
            for j in range(nd):
                comps[1 + j] = vels[j] + sgn * half_d[1 + j] + sv0[j] * dtdx2
            comps[ip] = p + sgn * half_d[ip] + sp0 * dtdx2
            for n in range(cfg.nener):
                comps[ip + 1 + n] = (q[ip + 1 + n] + sgn * half_d[ip + 1 + n]
                                     + se0[n] * dtdx2)
            for s in range(cfg.npassive):
                i = ip + 1 + cfg.nener + s
                comps[i] = q[i] + sgn * half_d[i] + sa0[s] * dtdx2
            return jnp.stack(comps)

        qm.append(build(+1.0))   # high-side face state
        qp.append(build(-1.0))   # low-side face state
    return jnp.stack(qm), jnp.stack(qp)


def trace_plmde(q, c, dq, dt, dx: Sequence[float], cfg: HydroStatic):
    """PLMDE predictor: per-direction characteristic projection
    (``hydro/uplmde.f90`` tracex/tracexy/tracexyz unified over ndim).

    Unlike the MUSCL-Hancock trace, each direction's face states are
    built by projecting the (ρ, v_n, P) slopes onto the acoustic
    characteristics and keeping only the waves that reach the face
    (``project_out`` = 1 drops outgoing ones); tangential velocities,
    non-thermal energies, and passives ride the entropy wave.  Returns
    (qm, qp) in the :func:`trace` convention — ``qm[d]`` the high-side
    face state, ``qp[d]`` the low-side one.
    """
    nd = cfg.ndim
    ip = nd + 1
    r = q[0]
    p = q[ip]
    csq = cfg.gamma * p / jnp.maximum(r, cfg.smallr)
    qm, qp = [], []
    for d in range(nd):
        dtdx = dt / dx[d]
        u = q[1 + d]
        dr = dq[d][0]
        du = dq[d][1 + d]
        dp = dq[d][ip]
        # supersonic fix: strong velocity gradients drop the acoustic
        # spread (uplmde.f90 'Supersonic fix')
        ccc = jnp.where(jnp.abs(du) > 3.0 * c, 0.0, c)
        alpham = 0.5 * (dp / csq - du * r / c)
        alphap = 0.5 * (dp / csq + du * r / c)
        alpha0 = dr - dp / csq

        def face(sgn):
            # sgn=-1: right state at the LOW face (left-moving waves);
            # sgn=+1: left state at the HIGH face (right-moving waves)
            if sgn < 0:
                spp = jnp.where(u + ccc > 0.0, -1.0, (u + ccc) * dtdx)
                spm = jnp.where(u - ccc > 0.0, -1.0, (u - ccc) * dtdx)
                spz = jnp.where(u > 0.0, -1.0, u * dtdx)
                wp = 0.5 * (-1.0 - spp)
                wm = 0.5 * (-1.0 - spm)
                wz = 0.5 * (-1.0 - spz)
            else:
                spp = jnp.where(u + ccc <= 0.0, 1.0, (u + ccc) * dtdx)
                spm = jnp.where(u - ccc <= 0.0, 1.0, (u - ccc) * dtdx)
                spz = jnp.where(u <= 0.0, 1.0, u * dtdx)
                wp = 0.5 * (1.0 - spp)
                wm = 0.5 * (1.0 - spm)
                wz = 0.5 * (1.0 - spz)
            ap = wp * alphap
            am = wm * alpham
            az = wz * alpha0
            comps = [None] * q.shape[0]
            comps[0] = jnp.maximum(r + (ap + am + az), cfg.smallr)
            comps[1 + d] = u + (ap - am) * c / r
            comps[ip] = p + (ap + am) * csq
            for j in range(q.shape[0]):
                if comps[j] is None:     # entropy-wave riders
                    comps[j] = q[j] + wz * dq[d][j]
            return jnp.stack(comps)

        qm.append(face(+1.0))
        qp.append(face(-1.0))
    return jnp.stack(qm), jnp.stack(qp)


def _iface_perm(cfg: HydroStatic, d: int) -> List[int]:
    """State-layout → interface-layout component permutation for dir d.

    Interface layout (riemann.py): rho, v_norm, P, v_tang..., nener, passive.
    Matches cmpflxm's (ln,lt1,lt2) gather (``hydro/umuscl.f90:96-105``).
    """
    tang = [j for j in range(cfg.ndim) if j != d]
    perm = [0, 1 + d, cfg.ndim + 1] + [1 + t for t in tang]
    perm += list(range(cfg.ndim + 2, cfg.nvar))
    return perm


def _inv_perm(perm: List[int]) -> List[int]:
    inv = [0] * len(perm)
    for i, pi in enumerate(perm):
        inv[pi] = i
    return inv


def face_fluxes(qm, qp, cfg: HydroStatic):
    """Godunov fluxes on all faces of every direction (``cmpflxm``).

    ``flux[d]`` is defined at the LOW face of each cell: interface between
    cell (i-1, i) along axis d, stored at index i.  Returns
    (flux [ndim, nvar, *sp], tmp [ndim, 2, *sp]) where tmp[:,0] is the face
    normal velocity (for div.u) and tmp[:,1] the internal-energy flux —
    the reference's ``tmp`` array for the dual-energy pressure fix.
    """
    fluxes, tmps = [], []
    for d in range(cfg.ndim):
        ax = _axis(cfg, d, qm[d])
        perm = _iface_perm(cfg, d)
        ql = jnp.roll(qm[d], 1, axis=ax)[jnp.array(perm)]
        qr = qp[d][jnp.array(perm)]
        fg = rsolve.solve(ql, qr, cfg)
        # scatter flux back to state layout: fg = [mass, mom_n, E, tang...,
        # nener..., passives..., eint]
        out = [None] * cfg.nvar
        out[0] = fg[0]
        out[1 + d] = fg[1]
        out[cfg.ndim + 1] = fg[2]
        tang = [j for j in range(cfg.ndim) if j != d]
        for k, t in enumerate(tang):
            out[1 + t] = fg[3 + k]
        for k in range(cfg.nener + cfg.npassive):
            out[cfg.ndim + 2 + k] = fg[2 + cfg.ndim + k]
        fluxes.append(jnp.stack(out))
        tmps.append(jnp.stack([0.5 * (ql[1] + qr[1]), fg[cfg.nvar]]))
    return jnp.stack(fluxes), jnp.stack(tmps)


def unsplit(u, grav, dt, dx: Sequence[float], cfg: HydroStatic):
    """One unsplit MUSCL-Hancock step on a ghost-padded grid.

    Equivalent of ``unsplit`` (``hydro/umuscl.f90:22-171``): returns
    per-direction face fluxes already scaled by dt/dx, plus the tmp array.
    The conservative update itself is :func:`apply_fluxes`.
    """
    q, c = ctoprim(u, grav, dt, cfg)
    dq = uslope(q, cfg)
    if cfg.scheme == "muscl":
        qm, qp = trace(q, dq, dt, dx, cfg)
    elif cfg.scheme == "plmde":
        qm, qp = trace_plmde(q, c, dq, dt, dx, cfg)
    else:
        raise NotImplementedError(f"scheme={cfg.scheme}")
    flux, tmp = face_fluxes(qm, qp, cfg)
    scale = jnp.stack([jnp.full((), dt / dx[d], u.dtype)
                       for d in range(cfg.ndim)])
    bshape = (cfg.ndim,) + (1,) * (flux.ndim - 1)
    return flux * scale.reshape(bshape), tmp * scale.reshape(bshape)


def eint_of(u, cfg: HydroStatic):
    """Thermal internal energy density from a conservative state."""
    r = jnp.maximum(u[0], cfg.smallr)
    e = u[cfg.ndim + 1] - sum(0.5 * u[1 + d] ** 2
                              for d in range(cfg.ndim)) / r
    for n in range(cfg.nener):
        e = e - u[cfg.ndim + 2 + n]
    return e


def dual_energy_fix(up, un, tmp, dt, dx: Sequence[float],
                    cfg: HydroStatic, hexp: float = 0.0):
    """Dual-energy pressure fix + non-thermal pdV sources on a padded
    block (``pressure_fix`` machinery of ``hydro/godunov_fine.f90``:
    divu/enew accumulation :735-790, ``add_pdv_source_terms`` :294-430,
    the set_uold correction :203-226).

    ``up``: padded OLD state; ``un``: padded UPDATED state (same
    layout); ``tmp``: per-direction [2, ...] (face normal velocity,
    internal-energy flux), both ×dt/dx as returned by :func:`unsplit`.
    Valid on the active interior (ghost results are wrapped garbage,
    like :func:`apply_fluxes`).  Returns ``un`` with the corrected
    total energy and pdV-updated non-thermal energies.
    """
    nd = cfg.ndim
    ie = nd + 1
    dt = jnp.asarray(dt, up.dtype)     # keep the state dtype (f32 runs)
    r_old = jnp.maximum(up[0], cfg.smallr)
    eint_old = eint_of(up, cfg)

    # field arrays ([*sp] / [*sp, batch]) drop the leading nvar axis of
    # the state layout _axis describes
    def axf(d):
        return _axis(cfg, d, up) - 1

    # face-flux accumulation: enew advection + divu (= -div·u·dt)
    enew = eint_old
    divu_acc = jnp.zeros_like(eint_old)
    for d in range(nd):
        ax = axf(d)
        enew = enew + (tmp[d][1] - jnp.roll(tmp[d][1], -1, axis=ax))
        divu_acc = divu_acc + (tmp[d][0]
                               - jnp.roll(tmp[d][0], -1, axis=ax))

    # centered -pdV source from the OLD velocity field
    # (add_pdv_source_terms' Trace G over 2dx)
    divu_c = jnp.zeros_like(eint_old)
    for d in range(nd):
        ax = axf(d)
        v = up[1 + d] / r_old
        divu_c = divu_c + (jnp.roll(v, -1, axis=ax)
                           - jnp.roll(v, 1, axis=ax)) / (2.0 * dx[d])
    enew = enew - (cfg.gamma - 1.0) * eint_old * divu_c * dt
    for n in range(cfg.nener):
        i = nd + 2 + n
        un = un.at[i].add(-(cfg.gamma_rad[n] - 1.0) * up[i]
                          * divu_c * dt)

    if not cfg.pressure_fix:
        return un

    # truncation test on the UPDATED state
    r_new = jnp.maximum(un[0], cfg.smallr)
    ekin_new = sum(0.5 * un[1 + d] ** 2 for d in range(nd)) / r_new
    for n in range(cfg.nener):
        ekin_new = ekin_new + un[nd + 2 + n]
    e_cons = un[ie] - ekin_new
    div = jnp.abs(divu_acc) * dx[0] / jnp.maximum(dt, 1e-300)
    e_trunc = cfg.beta_fix * r_new * jnp.maximum(
        div, 3.0 * hexp * dx[0]) ** 2
    fixed = jnp.where(e_cons < e_trunc, enew + ekin_new, un[ie])
    return un.at[ie].set(fixed)


def apply_fluxes(u, flux, cfg: HydroStatic):
    """Conservative update ``u += F_low - F_high`` per direction
    (``hydro/godunov_fine.f90:749-792``).  Valid on the active interior;
    the outermost ghost layers hold wrapped garbage."""
    unew = u
    for d in range(cfg.ndim):
        ax = _axis(cfg, d, u)
        unew = unew + (flux[d] - jnp.roll(flux[d], -1, axis=ax))
    return unew
