"""Minimal in-repo Adam optimizer (no external optimizer dependency).

Pure pytree-to-pytree functions: state and parameters are arbitrary
pytrees of arrays, every update is elementwise, so a batch of B
independent calibrations is just leaves with a leading ``[B]`` axis —
no vmap plumbing needed in the optimizer itself (Kingma & Ba 2014,
arXiv:1412.6980, the standard bias-corrected form).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    """First/second moment estimates + shared step counter."""
    m: Any
    v: Any
    count: Any  # int32 scalar


def adam_init(theta) -> AdamState:
    zeros = jax.tree_util.tree_map(jnp.zeros_like, theta)
    return AdamState(m=zeros,
                     v=jax.tree_util.tree_map(jnp.zeros_like, theta),
                     count=jnp.zeros((), jnp.int32))


def adam_update(grads, state: AdamState, theta, lr: float = 1e-2,
                b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    """One Adam step.  Returns ``(theta_new, state_new)``."""
    count = state.count + 1
    cf = count.astype(jnp.float64 if jax.config.jax_enable_x64
                      else jnp.float32)
    m = jax.tree_util.tree_map(
        lambda mu, g: b1 * mu + (1.0 - b1) * g, state.m, grads)
    v = jax.tree_util.tree_map(
        lambda nu, g: b2 * nu + (1.0 - b2) * (g * g), state.v, grads)

    def upd(p, mu, nu):
        mhat = mu / (1.0 - b1 ** cf)
        vhat = nu / (1.0 - b2 ** cf)
        return p - lr * mhat / (jnp.sqrt(vhat) + eps)

    theta = jax.tree_util.tree_map(upd, theta, m, v)
    return theta, AdamState(m=m, v=v, count=count)


def global_norm(grads, axis=None):
    """sqrt(sum of squares) over every leaf; with ``axis`` kept (e.g. a
    leading member axis), reduces each leaf over all *other* axes so the
    result is a per-member gradient norm."""
    total = 0.0
    for g in jax.tree_util.tree_leaves(grads):
        if axis is None:
            total = total + jnp.sum(g * g)
        else:
            red = tuple(a for a in range(g.ndim) if a != axis)
            total = total + jnp.sum(g * g, axis=red)
    return jnp.sqrt(total)


def clip_by_global_norm(grads, max_norm: float, axis=None):
    """Scale ``grads`` so the (per-member) global norm is <= max_norm."""
    norm = global_norm(grads, axis=axis)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-30))

    def apply(g):
        if axis is None or g.ndim == 0:
            return g * scale
        shp = [1] * g.ndim
        shp[axis] = -1
        return g * scale.reshape(shp)

    return jax.tree_util.tree_map(apply, grads), norm
