"""Run the rule registry over a program set + the source tree.

The one orchestration layer every gate shares: ``tools/lint.py``
(CLI / CI), the nightly gather gate, the multichip dryrun's lint leg,
and the telemetry run-header hook all call :func:`run` or
:func:`audit_program` so there is exactly one implementation of "what
does a clean program look like".
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ramses_tpu.analysis.rules import (Finding, Severity, all_rules,
                                       load_baseline, severity_counts,
                                       split_baselined)


def audit_program(program) -> List[Finding]:
    """All HLO-rule findings for one lowered program (duck-typed:
    ``.name``/``.text``/``.meta``)."""
    out: List[Finding] = []
    for rule in all_rules():
        if rule.kind == "hlo":
            out.extend(rule.check(program))
    return out


def audit_sim(sim, text: Optional[str] = None) -> Dict[str, int]:
    """Severity counts of the HLO audit of ``sim``'s own fused step —
    the telemetry run-header ``analysis_findings`` payload (accepted
    baseline findings excluded, so the header reports the *new*
    hazard state of the exact program the run measures).  ``text``
    reuses an already-held lowering instead of re-tracing."""
    from ramses_tpu.analysis.programs import sim_program
    findings = audit_program(sim_program(sim, text=text))
    new, _accepted = split_baselined(findings, load_baseline())
    return severity_counts(new)


def run(programs, source_root: Optional[str] = None,
        rule_ids: Optional[List[str]] = None) -> List[Finding]:
    """Every finding from every registered rule: HLO rules over each
    of ``programs``, source rules over the package tree (or
    ``source_root``)."""
    findings: List[Finding] = []
    for rule in all_rules():
        if rule_ids is not None and rule.id not in rule_ids:
            continue
        if rule.kind == "hlo":
            for prog in programs:
                findings.extend(rule.check(prog))
        else:
            findings.extend(rule.check(source_root))
    return findings


def report(findings: List[Finding],
           baseline_path: Optional[str] = None) -> Dict[str, Any]:
    """Machine-readable verdict: findings partitioned against the
    baseline plus severity counts — the ``tools/lint.py`` JSON
    shape."""
    baseline = load_baseline(baseline_path)
    new, accepted = split_baselined(findings, baseline)
    stale = sorted(set(baseline)
                   - {f.fingerprint for f in findings})
    return {
        "schema_version": 1,
        "counts": severity_counts(findings),
        "new_counts": severity_counts(new),
        "new": [f.to_json() for f in new],
        "accepted": [f.to_json() for f in accepted],
        "stale_baseline": stale,
        "ok": not any(f.severity >= Severity.WARN for f in new),
    }
