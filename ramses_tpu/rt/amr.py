"""Radiative transfer on the AMR hierarchy (M1 + thermochemistry).

The reference subcycles ``rt_step`` inside ``amr_step`` per level
(``amr/amr_step.f90:594-672``, ``rt/rt_godunov_fine.f90``).  Here the
radiation state lives as per-level flat rows next to the gas state and
advances at coarse-step cadence with RT-Courant substeps:

  * COMPLETE levels run the dense GLF transport of the uniform solver
    (:func:`ramses_tpu.rt.m1.transport_step`) on the permuted grid;
  * PARTIAL levels gather 6^d oct stencils with minmod-interpolated
    coarse ghosts (the same ``K._gather_uloc``/``K.interp_cells``
    machinery as the hydro sweep) and apply the GLF update on the
    block interior;
  * the photochemistry runs pointwise per level against the live gas
    density/temperature — the gray H-only system
    (:func:`ramses_tpu.rt.chem.chem_step`) or, with ``rt_ngroups>1`` /
    ``rt_y_he>0``, the multigroup 3-ion H/He/He+ ladder with
    blackbody-SED-averaged cross sections
    (:func:`ramses_tpu.rt.chem.chem_step_3ion`,
    ``rt/rt_spectra.f90`` + ``rt/rt_cooling_module.f90``); photoheating
    writes back into the gas energy;
  * restriction (``K.restrict_upload``) keeps covered cells at their
    son means after every substep.

Row layout: ``rad[l]`` is ``[ncell_pad, ngroups*(1+nd)]`` — group-major
(N, F_x..F_z) blocks, so every generic index kernel (gather, interp,
restriction, regrid migration) moves ALL groups in one call.  Photon
number at coarse-fine faces is first-order (no flux-correction
scatter) — leaves are authoritative and restriction re-syncs covered
cells, the standard relaxation.  Regrid migration rides the
hierarchy's logged migration maps exactly like the MHD face field.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr import kernels as K
from ramses_tpu.rt import chem as chem_mod
from ramses_tpu.rt import m1
from ramses_tpu.rt.driver import RtSpec
from ramses_tpu.units import X_frac, mH


from dataclasses import dataclass


@dataclass(frozen=True)
class _CfgShim:
    """ndim/nvar-only cfg for the generic gather/interp kernels —
    frozen so jit static-arg caching hits by VALUE (a fresh identity-
    hashed instance per call would retrace every kernel)."""
    ndim: int
    nvar: int


@partial(jax.jit, static_argnames=("nd", "c_red"))
def _glf_block(rad, dt_cgs, dx_cgs, c_red: float, nd: int):
    """GLF update on a gathered stencil block [1+nd, 6.., noct]
    (spatial axes 1..nd, trailing oct batch; ghosts provided by the
    gather, so no padding — the uniform ``transport_step`` without its
    pad/unpad).  Returns the updated block."""
    N = rad[0]
    F = [rad[1 + d] for d in range(nd)]
    U = [N] + F
    dN = jnp.zeros_like(N)
    dF = [jnp.zeros_like(N) for _ in range(nd)]
    for d in range(nd):
        ax = d                                  # field arrays: spatial
        flux = m1._phys_flux(N, F, c_red, nd, d)
        face = []
        for k in range(1 + nd):
            fl = jnp.roll(flux[k], 1, axis=ax)
            ul = jnp.roll(U[k], 1, axis=ax)
            face.append(0.5 * (fl + flux[k]) - 0.5 * c_red * (U[k] - ul))
        dN = dN + (dt_cgs / dx_cgs) * (face[0]
                                       - jnp.roll(face[0], -1, axis=ax))
        for j in range(nd):
            dF[j] = dF[j] + (dt_cgs / dx_cgs) * (
                face[1 + j] - jnp.roll(face[1 + j], -1, axis=ax))
    N_new = jnp.maximum(N + dN, m1.SMALL_NP)
    F_new = [F[j] + dF[j] for j in range(nd)]
    fmag = jnp.sqrt(sum(f ** 2 for f in F_new))
    cap = c_red * N_new
    scale = jnp.where(fmag > cap, cap / jnp.maximum(fmag, m1.SMALL_NP),
                      1.0)
    return jnp.stack([N_new] + [f * scale for f in F_new])


class RtAmrCoupled:
    """Owns the per-level radiation rows of an :class:`AmrSim`."""

    def __init__(self, sim, params, un):
        spec = RtSpec.from_params(params)
        self.spec = spec
        self.un = un
        self.params = params
        nd = sim.cfg.ndim
        self.nd = nd
        # multigroup/He surface: ng group-major (N, F) blocks per row,
        # He ion fractions ride a companion [ncp, 2] array
        self.full3 = spec.full3
        self.ng = len(spec.groups3) if self.full3 else 1
        self.x_frac = (1.0 - spec.y_he if spec.y_he > 0 else X_frac)
        # rad rows: [ncell_pad, ng*(1+nd)] = per group (N [1/cm^3],
        # F [1/cm^2/s])
        self.rad: Dict[int, jnp.ndarray] = {}
        self.xion: Dict[int, jnp.ndarray] = {}
        self.xhe: Dict[int, jnp.ndarray] = {}
        for l in sim.levels():
            ncp = sim.maps[l].ncell_pad
            self.rad[l] = jnp.asarray(self._fresh_rad(ncp))
            self.xion[l] = self._fresh_x(ncp)
            if self.full3:
                self.xhe[l] = self._fresh_he(ncp)
        # point source → NGP cell at its finest covering level
        self.src: Dict[int, jnp.ndarray] = {}
        r = params.rt
        if float(r.rt_ndot) > 0.0:
            from ramses_tpu.pm.amr_pm import assign_levels
            from ramses_tpu.pm.amr_physics import ngp_rows
            pos = np.asarray([[float(v) * sim.boxlen
                               for v in r.rt_src_pos[:nd]]])
            lsrc = int(assign_levels(sim.tree, pos, sim.boxlen)[0])
            row = int(ngp_rows(sim.tree, pos, lsrc, sim.boxlen,
                               sim.bc_kinds)[0])
            vol_cgs = (sim.dx(lsrc) * un.scale_l) ** nd
            self._src_info = (lsrc, row, float(r.rt_ndot) / vol_cgs)
        else:
            self._src_info = None
        # stellar SED tables (rt/rt_spectra.f90): star particles become
        # photon sources with age/metallicity-dependent rates, and the
        # population refreshes the chemistry's group cross-sections
        import os as _os
        self.sed = None
        if r.sed_dir or _os.environ.get("RAMSES_SED_DIR"):
            from ramses_tpu.rt.sed import SedTables, read_sed_dir
            g3 = spec.groups3
            bounds = [g.e_lo for g in g3] + [g3[-1].e_hi]
            self.sed = SedTables(read_sed_dir(r.sed_dir), bounds)
        self._esc = float(getattr(r, "rt_esc_frac", 1.0))
        self._sed_update = max(1, int(getattr(r, "sedprops_update", 5)))
        self._sed_count = 0
        self._star_src = {}
        self._sink_src = {}
        # cumulative photons injected by all sources [photons], the
        # denominator of the rt_stats conservation ratio
        self._injected = 0.0
        # homogeneous UV background (rt_UV_hom): amplitude follows the
        # cooling module's J21/a_spec/z_reion epoch dependence
        self.uv_on = bool(getattr(r, "rt_uv_hom", False))
        self._uv = None

    def _refresh_stellar_sources(self, sim):
        """Rebuild per-level stellar injection lists from the SED tables
        and, at the ``sedprops_update`` cadence, refresh the chemistry's
        group properties to the population's photon-rate-weighted
        average (``rt_spectra.f90`` star_RT_feedback +
        update_SED_group_props roles)."""
        self._star_src = {}
        if self.sed is None or sim.p is None:
            return
        from ramses_tpu.pm.amr_pm import assign_levels
        from ramses_tpu.pm.amr_physics import ngp_rows
        from ramses_tpu.pm.particles import FAM_STAR
        from ramses_tpu.pm.star_formation import M_SUN
        p = sim.p
        sel = np.asarray((p.family == FAM_STAR) & p.active)
        if not sel.any():
            return
        un = self.un
        GYR = 3.15576e16
        age_gyr = np.maximum(
            (sim.t - np.asarray(p.tp)[sel]) * un.scale_t / GYR, 0.0)
        zmet = np.asarray(p.zp)[sel]
        m_sun = np.asarray(p.m)[sel] * un.scale_d \
            * un.scale_l ** self.nd / M_SUN
        rates = self.sed.star_rates(age_gyr, zmet, m_sun) * self._esc
        pos = np.asarray(p.x)[sel]
        levs = assign_levels(sim.tree, pos, sim.boxlen)
        for l in sim.levels():
            at_l = levs == l
            if not at_l.any():
                continue
            rows = ngp_rows(sim.tree, pos[at_l], l, sim.boxlen,
                            sim.bc_kinds)
            ok = rows >= 0
            if not ok.any():
                continue
            vol = (sim.dx(l) * un.scale_l) ** self.nd
            self._star_src[l] = (jnp.asarray(rows[ok]),
                                 jnp.asarray(rates[at_l][ok] / vol))
        if self._sed_count % self._sed_update == 0:
            import dataclasses
            g3 = self.sed.population_groups(age_gyr, zmet, m_sun)
            if self.full3:
                self.spec = dataclasses.replace(self.spec, groups3=g3)
            else:
                # gray chemistry consumes spec.group, not groups3
                from ramses_tpu.rt.chem import GroupSpec
                self.spec = dataclasses.replace(
                    self.spec, groups3=g3,
                    group=GroupSpec(sigma=g3[0].sigmaN[0],
                                    e_photon=g3[0].e_photon))
        self._sed_count += 1

    def _refresh_sink_sources(self, sim):
        """Sink RT (HII) feedback: sink-spawned stellar objects emit
        ionizing photons into their sink's NGP cell while younger than
        ``hii_t`` — the Vacca+96 ionizing-flux fit
        ``S(M) = stf_K·(M/m0)^a/(1+(M/m0)^b)^c``
        (``pm/sink_rt_feedback.f90`` ``gather_ioni_flux`` +
        ``sink_RT_vsweep_stellar``; the reference splits S over the
        sink's cloud particles, whose NGP cells collapse to the sink's
        cell at the deposit level — the single-cell limit here)."""
        self._sink_src = {}
        st = getattr(sim, "stellar", None)
        sp = getattr(sim, "stellar_spec", None)
        if (st is None or st.n == 0 or sim.sinks is None
                or sp is None or sp.hii_t_myr <= 0.0):
            return
        MYR = 3.15576e13
        age_s = (sim.t - st.tform) * self.un.scale_t
        live = age_s < sp.hii_t_myr * MYR
        if not live.any():
            return
        m = st.m[live]
        S = sp.stf_k * (m / sp.stf_m0) ** sp.stf_a \
            / (1.0 + (m / sp.stf_m0) ** sp.stf_b) ** sp.stf_c
        # photons follow the sink's CURRENT position, not the birth one
        sink_of = {int(i): k for k, i in enumerate(sim.sinks.idp)}
        snk = np.array([sink_of.get(int(s), -1)
                        for s in st.sink_idp[live]])
        ok = snk >= 0
        if not ok.any():
            return
        pos = np.asarray(sim.sinks.x)[snk[ok]]
        S = S[ok]
        from ramses_tpu.pm.amr_pm import assign_levels
        from ramses_tpu.pm.amr_physics import ngp_rows
        levs = assign_levels(sim.tree, pos, sim.boxlen)
        gidx = min(max(sp.fb_group, 0), self.ng - 1)
        for l in sim.levels():
            at_l = levs == l
            if not at_l.any():
                continue
            rows = ngp_rows(sim.tree, pos[at_l], l, sim.boxlen,
                            sim.bc_kinds)
            okr = rows >= 0
            if not okr.any():
                continue
            vol = (sim.dx(l) * self.un.scale_l) ** self.nd
            dens = np.zeros((int(okr.sum()), self.ng))
            dens[:, gidx] = S[at_l][okr] / vol
            self._sink_src[l] = (jnp.asarray(rows[okr]),
                                 jnp.asarray(dens))

    def _fresh_rad(self, ncp: int) -> np.ndarray:
        """Vacuum radiation rows [ncp, ng*(1+nd)]."""
        rad = np.zeros((ncp, self.ng * (1 + self.nd)))
        rad[:, ::1 + self.nd] = m1.SMALL_NP          # N columns
        return rad

    def photon_total(self, sim) -> float:
        """Total photon count over leaf cells, all groups (Σ N·dV)."""
        tot = 0.0
        for l in sim.levels():
            rad = sim.tree_order_cells(self.rad[l], l)
            leaf = ~sim.tree.refined_mask(l)
            dv = (sim.dx(l) * self.un.scale_l) ** self.nd
            for g in range(self.ng):
                tot += float(np.sum(rad[leaf, self._ncol(g)])) * dv
        return tot

    def rt_stats(self, sim) -> dict:
        """Photon-budget stats for the screen block (the reference's
        ``output_rt_stats`` role, ``amr/amr_step.f90:467``): live photon
        count vs cumulative injected; the ratio falls below 1 as gas
        absorbs (and is ~1 for free streaming)."""
        tot = self.photon_total(sim)
        inj = float(self._injected)
        return {"photons": tot, "injected": inj,
                "ratio": (tot / inj) if inj > 0.0 else 0.0}

    @staticmethod
    def _fresh_x(ncp: int) -> jnp.ndarray:
        """Initial HII fraction rows (the reference's x_ini)."""
        return jnp.full((ncp,), 1.2e-3)

    @staticmethod
    def _fresh_he(ncp: int) -> jnp.ndarray:
        """Initial (HeII, HeIII) fraction rows."""
        return jnp.asarray(np.tile([1e-6, 1e-8], (ncp, 1)))

    def _ncol(self, g: int) -> int:
        """Column of group ``g``'s photon density N."""
        return g * (1 + self.nd)

    # ------------------------------------------------------------------
    def _mu(self, l):
        """Mean molecular weight rows from the current ion state
        (``rt_cooling_module``'s getMu with mass fractions X/Y)."""
        x = self.xion[l]
        y = self.spec.y_he
        if self.full3 and y > 0:
            xh2, xh3 = self.xhe[l][:, 0], self.xhe[l][:, 1]
            denom = (1.0 - y) * (1.0 + x) + 0.25 * y * (1.0 + xh2
                                                        + 2.0 * xh3)
        else:
            denom = 1.0 + x
        return 1.0 / jnp.maximum(denom, 1e-10)

    def _gas_nT(self, sim, l):
        """(nH [1/cc], T [K]) rows of level ``l`` from the gas state."""
        cfg = sim.cfg
        u = sim.u[l]
        rho = jnp.maximum(u[:, 0], cfg.smallr)
        mom2 = sum(u[:, 1 + d] ** 2 for d in range(cfg.ndim))
        eint = jnp.maximum(u[:, cfg.ndim + 1] - 0.5 * mom2 / rho, 1e-300)
        t2 = (cfg.gamma - 1.0) * eint / rho * self.un.scale_T2
        nH = rho * self.un.scale_d * self.x_frac / mH
        return nH, jnp.maximum(t2 * self._mu(l), 0.1)

    def advance(self, sim, dt_code: float):
        """Subcycled RT over one coarse step against the live gas;
        writes photoheated energy back into ``sim.u``."""
        spec = self.spec
        nd = self.nd
        if sim.cosmo is not None:
            # supercomoving unit scales are aexp-dependent: refresh
            # (cf. the cooling-scale refresh in step_coarse)
            from ramses_tpu.units import units as units_fn
            self.un = units_fn(self.params, cosmo=sim.cosmo,
                               aexp=sim.aexp_now())
        lmax_used = max(sim.levels())
        dx_min_cgs = sim.dx(lmax_used) * self.un.scale_l
        dt_cgs = float(dt_code) * self.un.scale_t
        dt_c = m1.rt_courant_dt(dx_min_cgs, spec.c_red, spec.courant)
        nsub = max(1, int(np.ceil(dt_cgs / dt_c)))
        dt_sub = dt_cgs / nsub
        self._refresh_stellar_sources(sim)
        self._refresh_sink_sources(sim)
        spec = self.spec              # groups3 may have been refreshed
        if self.uv_on:
            from ramses_tpu.hydro.cooling import uv_amplitude, uv_rates
            c = self.params.cooling
            aexp = sim.aexp_now() if sim.cosmo is not None else 1.0
            J = uv_amplitude(aexp, float(c.J21), float(c.z_reion),
                             bool(c.haardt_madau))
            if J > 0.0:
                g, h = uv_rates(J, float(c.a_spec))
                self._uv = ((g.get("HI", 0.0), g.get("HeI", 0.0),
                             g.get("HeII", 0.0)),
                            (h.get("HI", 0.0), h.get("HeI", 0.0),
                             h.get("HeII", 0.0)))
            else:
                self._uv = None

        nT = {l: self._gas_nT(sim, l) for l in sim.levels()}
        T = {l: nT[l][1] for l in sim.levels()}
        T0 = dict(T)

        # photon-budget accounting (rt_stats): source rates are photon
        # DENSITY rates [1/cm^3/s]; × cell volume × dt gives counts
        if self._src_info is not None:
            lsrc, _row, rate = self._src_info
            vol = (sim.dx(lsrc) * self.un.scale_l) ** nd
            frac = sum(g.frac for g in spec.groups3) if self.full3 else 1.0
            self._injected += rate * vol * dt_cgs * frac
        for srcmap in (self._star_src, self._sink_src):
            for l, (_rows, dens) in srcmap.items():
                vol = (sim.dx(l) * self.un.scale_l) ** nd
                self._injected += float(jnp.sum(dens)) * vol * dt_cgs

        ng = self.ng
        ncols = ng * (1 + nd)
        for _ in range(nsub):
            # sources (multigroup: split by the SED's photon shares)
            if self._src_info is not None:
                lsrc, row, rate = self._src_info
                if self.full3:
                    for g, grp in enumerate(spec.groups3):
                        self.rad[lsrc] = self.rad[lsrc].at[
                            row, self._ncol(g)].add(
                                dt_sub * rate * grp.frac)
                else:
                    self.rad[lsrc] = self.rad[lsrc].at[row, 0].add(
                        dt_sub * rate)
            # stellar sources (SED tables: per-star per-group rates)
            # + sink-spawned stellar objects (Vacca fit, _sink_src)
            for srcmap in (self._star_src, self._sink_src):
                for l, (rows, dens) in srcmap.items():
                    rad = self.rad[l]
                    if self.full3:
                        for g in range(ng):
                            rad = rad.at[rows, self._ncol(g)].add(
                                dt_sub * dens[:, g])
                    else:
                        rad = rad.at[rows, 0].add(
                            dt_sub * dens.sum(axis=1))
                    self.rad[l] = rad
            # transport, coarse→fine (every group; one gather moves
            # all group blocks, the GLF update runs per group)
            for l in sim.levels():
                m = sim.maps[l]
                d = sim.dev[l]
                dx_cgs = sim.dx(l) * self.un.scale_l
                rad = self.rad[l]
                shim = _CfgShim(nd, ncols)
                if m.complete:
                    nb = 1 << l
                    shp = (nb,) * nd
                    sl = (sim._slab_spec(l) if spec.periodic else None)
                    if sl is not None:
                        # explicit slab-sharded transport: the GLF
                        # stencil is 1-deep, so one ring halo (DMA or
                        # ppermute per halo_backend) + the interior of
                        # an extended-box transport_step reproduces the
                        # global result (parallel/dense_slab.py)
                        from ramses_tpu.parallel import dense_slab

                        def _transport_local(ext, _dx=dx_cgs):
                            cols = []
                            for g in range(ng):
                                c0 = self._ncol(g)
                                N = ext[..., c0]
                                F = jnp.stack(
                                    [ext[..., c0 + 1 + c]
                                     for c in range(nd)])
                                N, F = m1.transport_step(
                                    N, F, dt_sub, _dx, spec.c_red, nd,
                                    periodic=True)
                                cols.append(N[..., None])
                                cols.extend(F[c][..., None]
                                            for c in range(nd))
                            out = jnp.concatenate(cols, axis=-1)
                            return out[tuple(slice(1, -1)
                                             for _ in range(nd))]

                        rad = dense_slab.dense_apply_slab(
                            rad, sl, _transport_local, ng=1)
                        self.rad[l] = rad
                        continue
                    dense = K.rows_to_dense(rad, d.get("inv_perm"), shp)
                    cols = []
                    for g in range(ng):
                        c0 = self._ncol(g)
                        N = dense[..., c0]
                        F = jnp.stack([dense[..., c0 + 1 + c]
                                       for c in range(nd)])
                        N, F = m1.transport_step(
                            N, F, dt_sub, dx_cgs, spec.c_red, nd,
                            periodic=spec.periodic)
                        cols.append(N[..., None])
                        cols.extend(F[c][..., None] for c in range(nd))
                    rows = K.dense_to_rows(
                        jnp.concatenate(cols, axis=-1), d.get("perm"),
                        shp)
                    ncell = m.noct * (1 << nd)
                    if m.ncell_pad > ncell:
                        rad = rad.at[:ncell].set(rows)
                    else:
                        rad = rows
                else:
                    ghosts = K.interp_cells(
                        self.rad[l - 1], d["interp_cell"],
                        d["interp_nb"],
                        d["interp_sgn"].astype(rad.dtype), shim,
                        itype=1)
                    blk = K._gather_uloc(rad, ghosts, d["stencil_src"],
                                         None, shim)
                    blk = jnp.concatenate(
                        [_glf_block(blk[self._ncol(g):self._ncol(g + 1)],
                                    dt_sub, dx_cgs, spec.c_red, nd)
                         for g in range(ng)], axis=0)
                    interior = (slice(None),) + tuple(
                        slice(2, 4) for _ in range(nd))
                    noct = blk.shape[-1]
                    # oct-major flat rows, like level_sweep's du
                    # extraction (amr/kernels.py): [noct*2^d, ncols]
                    upd = jnp.transpose(
                        blk[interior],
                        (nd + 1,) + tuple(range(1, nd + 1)) + (0,)
                    ).reshape(noct * 2 ** nd, ncols)
                    rad = rad.at[:noct * 2 ** nd].set(upd)
                self.rad[l] = rad
            # chemistry per level (pointwise; leaves authoritative)
            for l in sim.levels():
                nH, _T = nT[l]
                if self.full3:
                    nHe = nH * (spec.y_he
                                / (4.0 * max(1.0 - spec.y_he, 1e-10)))
                    Ns = [self.rad[l][:, self._ncol(g)]
                          for g in range(ng)]
                    Ns, (x, xh2, xh3), Tn = chem_mod.chem_step_3ion(
                        Ns, (self.xion[l], self.xhe[l][:, 0],
                             self.xhe[l][:, 1]), T[l], nH, nHe,
                        dt_sub, spec.c_red, spec.groups3, spec.otsa,
                        heating=spec.heating, uv=self._uv)
                    rad = self.rad[l]
                    for g in range(ng):
                        rad = rad.at[:, self._ncol(g)].set(Ns[g])
                    self.rad[l] = rad
                    self.xhe[l] = jnp.stack([xh2, xh3], axis=1)
                else:
                    N, x, Tn = chem_mod.chem_step(
                        self.rad[l][:, 0], self.xion[l], T[l], nH,
                        dt_sub, spec.c_red, spec.group, spec.otsa,
                        heating=spec.heating, uv=self._uv)
                    self.rad[l] = self.rad[l].at[:, 0].set(N)
                self.xion[l] = x
                T[l] = Tn
            # restriction fine→coarse
            for l in sorted(sim.levels(), reverse=True):
                if sim.tree.has(l + 1):
                    d = sim.dev[l]
                    self.rad[l] = K.restrict_upload(
                        self.rad[l], self.rad[l + 1], d["ref_cell"],
                        d["son_oct"], _CfgShim(nd, ncols))
                    self.xion[l] = K.restrict_upload(
                        self.xion[l][:, None], self.xion[l + 1][:, None],
                        d["ref_cell"], d["son_oct"],
                        _CfgShim(nd, 1))[:, 0]
                    if self.full3:
                        self.xhe[l] = K.restrict_upload(
                            self.xhe[l], self.xhe[l + 1],
                            d["ref_cell"], d["son_oct"],
                            _CfgShim(nd, 2))

        if spec.heating:
            # write the integrated ΔT back into the gas energy
            for l in sim.levels():
                cfg = sim.cfg
                u = sim.u[l]
                rho = jnp.maximum(u[:, 0], cfg.smallr)
                dT2 = (T[l] - T0[l]) / self._mu(l)
                de = rho * dT2 / self.un.scale_T2 / (cfg.gamma - 1.0)
                sim.u[l] = u.at[:, cfg.ndim + 1].add(
                    de.astype(u.dtype))
            sim._dt_cache = None

    # ------------------------------------------------------------------
    def apply_migration(self, sim):
        """Carry rad/xion through a regrid using the hierarchy's logged
        migration maps (the MHD face-field pattern)."""
        from ramses_tpu.amr.hierarchy import _migrate_level

        nd = self.nd
        ncols = self.ng * (1 + nd)
        new_rad: Dict[int, jnp.ndarray] = {}
        new_x: Dict[int, jnp.ndarray] = {}
        new_he: Dict[int, jnp.ndarray] = {}
        for l in sim.levels():
            ncp = sim.maps[l].ncell_pad
            if l not in sim._mig_log:
                if l in self.rad and self.rad[l].shape[0] == ncp:
                    new_rad[l] = self.rad[l]
                    new_x[l] = self.xion[l]
                    if self.full3:
                        new_he[l] = self.xhe[l]
                else:                          # fresh level
                    new_rad[l] = jnp.asarray(self._fresh_rad(ncp))
                    new_x[l] = self._fresh_x(ncp)
                    if self.full3:
                        new_he[l] = self._fresh_he(ncp)
                continue
            (rows_d, rows_s, cell_rep, sgn_dev, rows_new, ncell_pad,
             _new_octs, _f_cell, nb_rep) = sim._mig_log[l]
            old_rad = self.rad.get(
                l, jnp.asarray(self._fresh_rad(1)))
            old_x = self.xion.get(l, self._fresh_x(1))
            new_rad[l] = _migrate_level(
                old_rad, new_rad[l - 1] if l - 1 in new_rad
                else self.rad[l - 1], rows_d, rows_s, cell_rep, nb_rep,
                sgn_dev, rows_new, ncell_pad, _CfgShim(nd, ncols), 1)
            new_x[l] = _migrate_level(
                old_x[:, None], (new_x[l - 1] if l - 1 in new_x
                                 else self.xion[l - 1])[:, None],
                rows_d, rows_s, cell_rep, nb_rep, sgn_dev, rows_new,
                ncell_pad, _CfgShim(nd, 1), 1)[:, 0]
            if self.full3:
                old_he = self.xhe.get(l, self._fresh_he(1))
                new_he[l] = _migrate_level(
                    old_he, new_he[l - 1] if l - 1 in new_he
                    else self.xhe[l - 1], rows_d, rows_s, cell_rep,
                    nb_rep, sgn_dev, rows_new, ncell_pad,
                    _CfgShim(nd, 2), 1)
        self.rad = new_rad
        self.xion = new_x
        self.xhe = new_he
        # the source cell may have moved levels/rows
        if self._src_info is not None:
            from ramses_tpu.pm.amr_pm import assign_levels
            from ramses_tpu.pm.amr_physics import ngp_rows
            r = self.params.rt
            pos = np.asarray([[float(v) * sim.boxlen
                               for v in r.rt_src_pos[:nd]]])
            lsrc = int(assign_levels(sim.tree, pos, sim.boxlen)[0])
            row = int(ngp_rows(sim.tree, pos, lsrc, sim.boxlen,
                               sim.bc_kinds)[0])
            vol_cgs = (sim.dx(lsrc) * self.un.scale_l) ** nd
            self._src_info = (lsrc, row,
                              float(r.rt_ndot) / vol_cgs)

    def ionized_volume(self, sim) -> float:
        """Σ x dV over leaves (the Strömgren measure, code volume)."""
        tot = 0.0
        for l in sim.levels():
            m = sim.maps[l]
            x = np.asarray(self.xion[l])[:m.noct * 2 ** self.nd]
            leaf = ~sim.tree.refined_mask(l)
            tot += float(x[leaf].sum()) * sim.dx(l) ** self.nd
        return tot
