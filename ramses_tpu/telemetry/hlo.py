"""Static HLO gather-traffic inventory.

The AMR per-cell gap is gather-bound: every partial-level sweep starts
from index gathers out of the flat cell batches, and the gathered
RESULT element count of the lowered program is a backend-independent
proxy for that HBM traffic — countable on the CPU test backend, stable
across XLA versions (it is read from the *lowered* StableHLO, before
the partitioner or fusion touch it).  The blocked Morton-tile path
exists to shrink exactly this number, so the regression test pins it
(tests/test_hlo_inventory.py) and the telemetry run header records it
(``hlo_gather_elems``) for offline trend tracking.
"""

from __future__ import annotations

import re
from typing import List, Tuple

# `stablehlo.gather ... -> tensor<AxBx...xf32>` (also matches the
# `"stablehlo.gather"(...)` generic-syntax form and dynamic_gather)
_GATHER_RE = re.compile(
    r"stablehlo\.(?:dynamic_)?gather\"?.*->\s*tensor<([0-9x]+)x?[a-z]")


def gather_inventory(text: str) -> List[Tuple[int, str]]:
    """All gather ops in lowered StableHLO/HLO ``text`` as
    ``(result_elems, op_line)`` pairs, largest first."""
    out = []
    for line in text.splitlines():
        m = _GATHER_RE.search(line)
        if not m:
            continue
        dims = [int(d) for d in m.group(1).split("x") if d]
        n = 1
        for d in dims:
            n *= d
        out.append((n, line.strip()[:200]))
    out.sort(key=lambda t: -t[0])
    return out


def count_gather_elems(text: str) -> int:
    """Total gathered RESULT elements across every gather op in lowered
    ``text``."""
    return sum(n for n, _ in gather_inventory(text))


def lower_fused_step(sim, dt: float = 1e-6) -> str:
    """Lowered (pre-optimization) StableHLO text of one fused AMR coarse
    step for ``sim``'s current tree — the program whose gather traffic
    the inventory counts.  Dispatches on the solver family: MHD sims
    (``sim.bfs``) lower the CT fused step."""
    import jax.numpy as jnp

    dt_arr = jnp.asarray(float(sim.dt_old or dt), sim.dtype)
    spec = sim._fused_spec()
    if hasattr(sim, "bfs"):
        from ramses_tpu.mhd import amr as M

        return M._mhd_fused_coarse_step.lower(
            sim.u, sim.bfs, sim.dev, dt_arr, spec,
            sim.fg if sim.gravity else None).as_text()
    from ramses_tpu.amr import hierarchy as H

    return H._fused_coarse_step.lower(
        sim.u, sim.dev, sim.fg if sim.gravity else {}, dt_arr, spec,
        sim._cool_bundle()).as_text()


def fused_step_gather_elems(sim) -> int:
    """``count_gather_elems`` of the sim's fused coarse step."""
    return count_gather_elems(lower_fused_step(sim))
