"""2D corner Riemann solvers for the CT edge EMF.

Counterpart of the reference's ``cmp_mag_flx``
(``mhd/umuscl.f90:1453-2024``; namelist ``riemann2d`` =
llf|roe|upwind|hll|hlla|hlld, mapping
``hydro/read_hydro_params.f90:207-221``).  The edge EMF is computed from
the FOUR states surrounding each cell edge instead of the
Gardiner-Stone arithmetic average — the upwinding that keeps strongly
magnetised shear flows (Orszag-Tang, loop advection) stable without the
GS correction terms.

States are labelled (x, y) with x in {L,R} the side along d1 and y in
{B,T} the side along d2.  The staggered fields at the edge are
single-valued per face: A = B_d1 on the two d1-faces (varies with y
only), B = B_d2 on the two d2-faces (varies with x only).

Solver families (all vectorized over the grid, ``jnp.where`` selection):

* ``hll`` / ``hlla`` — the four-state 2D-HLL average of Londrillo & Del
  Zanna (2004) with fast-magnetosonic / Alfven signal speeds.
* ``llf`` / ``roe`` / ``upwind`` — quarter-average of the four corner
  EMFs plus the DISSIPATIVE part of two orthogonal 1D solves on
  side-averaged states (the reference's ``zero_flux=0`` trick,
  ``mhd/umuscl.f90:1978``).
* ``hlld`` — the four-state HLLD with a contact (ustar, vstar), star
  states per quadrant, and Alfven-bounded inner waves
  (``mhd/umuscl.f90:1597-1805`` semantics, re-derived select-based).

Internally everything uses the reference EMF convention
eps = u*B - v*A (u = v_d1, v = v_d2); the caller converts to the code's
edge-EMF sign with ``e_edge = -sig * eps``.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp

from ramses_tpu.mhd import roe as roemod
from ramses_tpu.mhd.core import MhdStatic

_EPS = 1e-30

# quadrant keys
QUADS = (("L", "B"), ("R", "B"), ("L", "T"), ("R", "T"))


from ramses_tpu.mhd.riemann import _fast


def _alfven(r, bn, smallc):
    return jnp.sqrt(jnp.maximum(bn ** 2 / r, smallc ** 2))


def corner_emf(states: Dict[Tuple[str, str], Tuple], A_T, A_B, B_R, B_L,
               cfg: MhdStatic):
    """eps at each edge from the four surrounding corner states.

    ``states[(x, y)]`` = (r, p, u, v, w, c): density, pressure, the two
    in-plane velocities (u along d1, v along d2), the orthogonal
    velocity and the orthogonal cell field at the corner.  A_T/A_B:
    staggered B_d1 on the d2-above/below faces; B_R/B_L: staggered B_d2
    on the d1-right/left faces.  Returns eps = u*B - v*A upwinded per
    ``cfg.riemann2d``; the caller applies the orientation sign.
    """
    g = cfg.gamma
    sc = cfg.smallc
    rs = {k: jnp.maximum(s[0], cfg.smallr) for k, s in states.items()}
    ps = {k: jnp.maximum(s[1], cfg.smallr * sc ** 2)
          for k, s in states.items()}
    us = {k: s[2] for k, s in states.items()}
    vs = {k: s[3] for k, s in states.items()}
    ws = {k: s[4] for k, s in states.items()}
    cs = {k: s[5] for k, s in states.items()}
    A_of = {"B": A_B, "T": A_T}
    B_of = {"L": B_L, "R": B_R}
    eps = {k: us[k] * B_of[k[0]] - vs[k] * A_of[k[1]] for k in QUADS}

    kind = cfg.riemann2d
    if kind in ("hll", "hlla"):
        if kind == "hll":
            cx = {k: _fast(rs[k], ps[k], A_of[k[1]], B_of[k[0]], cs[k],
                           g, sc) for k in QUADS}
            cy = {k: _fast(rs[k], ps[k], B_of[k[0]], A_of[k[1]], cs[k],
                           g, sc) for k in QUADS}
        else:
            cx = {k: _alfven(rs[k], A_of[k[1]], sc) for k in QUADS}
            cy = {k: _alfven(rs[k], B_of[k[0]], sc) for k in QUADS}

        def mm(d):
            vals = list(d.values())
            lo = vals[0]
            hi = vals[0]
            for v in vals[1:]:
                lo = jnp.minimum(lo, v)
                hi = jnp.maximum(hi, v)
            return lo, hi

        umin, umax = mm(us)
        vmin, vmax = mm(vs)
        _, cxmax = mm(cx)
        _, cymax = mm(cy)
        SL = jnp.minimum(umin - cxmax, 0.0)
        SR = jnp.maximum(umax + cxmax, 0.0)
        SB = jnp.minimum(vmin - cymax, 0.0)
        ST = jnp.maximum(vmax + cymax, 0.0)
        dx_ = SR - SL + _EPS
        dy_ = ST - SB + _EPS
        # Londrillo & Del Zanna (2004) four-state 2D-HLL average
        return ((SL * SB * eps[("R", "T")] - SL * ST * eps[("R", "B")]
                 - SR * SB * eps[("L", "T")] + SR * ST * eps[("L", "B")])
                / (dx_ * dy_)
                - ST * SB / dy_ * (A_T - A_B)
                + SR * SL / dx_ * (B_R - B_L))

    if kind in ("llf", "roe", "upwind"):
        ebar = 0.25 * sum(eps.values())

        def avg(d, idx, side):
            ks = [k for k in QUADS if k[idx] == side]
            return 0.5 * (d[ks[0]] + d[ks[1]])

        # x-solve: rotated layout [rho, vn=u, vt1=v, vt2=w, P, Bn, Bt1=B,
        # Bt2=C] on y-averaged side states
        def pack_x(side):
            return jnp.stack([avg(rs, 0, side), avg(us, 0, side),
                              avg(vs, 0, side), avg(ws, 0, side),
                              avg(ps, 0, side), jnp.zeros_like(A_T),
                              B_of[side], avg(cs, 0, side)])

        def pack_y(side):
            return jnp.stack([avg(rs, 1, side), avg(vs, 1, side),
                              avg(us, 1, side), avg(ws, 1, side),
                              avg(ps, 1, side), jnp.zeros_like(A_T),
                              A_of[side], avg(cs, 1, side)])

        bn_x = 0.5 * (A_T + A_B)
        bn_y = 0.5 * (B_R + B_L)
        diss = {"llf": roemod.llf_dissipation,
                "roe": roemod.roe_dissipation,
                "upwind": roemod.upwind_dissipation}[kind]
        dx5 = diss(pack_x("L"), pack_x("R"), bn_x, cfg)[5]
        dy5 = diss(pack_y("B"), pack_y("T"), bn_y, cfg)[5]
        return ebar - dx5 + dy5

    if kind == "hlld":
        return _hlld2d(rs, ps, us, vs, cs, eps, A_of, B_of, cfg)

    raise NotImplementedError(f"riemann2d={kind!r}")


def _hlld2d(rs, ps, us, vs, cs, eps, A_of, B_of, cfg: MhdStatic):
    """Four-state HLLD corner EMF (contact + Alfven-bounded fan)."""
    g = cfg.gamma
    sc = cfg.smallc
    LB, RB, LT, RT = (("L", "B"), ("R", "B"), ("L", "T"), ("R", "T"))

    cx = {k: _fast(rs[k], ps[k], A_of[k[1]], B_of[k[0]], cs[k], g, sc)
          for k in (LB, RB, LT, RT)}
    cy = {k: _fast(rs[k], ps[k], B_of[k[0]], A_of[k[1]], cs[k], g, sc)
          for k in (LB, RB, LT, RT)}

    def extr(d, f):
        vals = list(d.values())
        out = vals[0]
        for v in vals[1:]:
            out = f(out, v)
        return out

    cxm = extr(cx, jnp.maximum)
    cym = extr(cy, jnp.maximum)
    SL = extr(us, jnp.minimum) - cxm
    SR = extr(us, jnp.maximum) + cxm
    SB = extr(vs, jnp.minimum) - cym
    ST = extr(vs, jnp.maximum) + cym

    ptot = {k: ps[k] + 0.5 * (A_of[k[1]] ** 2 + B_of[k[0]] ** 2
                              + cs[k] ** 2)
            for k in (LB, RB, LT, RT)}
    # mass-weighted contact speeds (the reference's ustar/vstar)
    rcx = {k: rs[k] * ((us[k] - SL) if k[0] == "L" else (SR - us[k]))
           for k in (LB, RB, LT, RT)}
    rcy = {k: rs[k] * ((vs[k] - SB) if k[1] == "B" else (ST - vs[k]))
           for k in (LB, RB, LT, RT)}
    ustar = ((sum(rcx[k] * us[k] for k in (LB, RB, LT, RT))
              + (ptot[LB] - ptot[RB] + ptot[LT] - ptot[RT]))
             / (sum(rcx.values()) + _EPS))
    vstar = ((sum(rcy[k] * vs[k] for k in (LB, RB, LT, RT))
              + (ptot[LB] - ptot[LT] + ptot[RB] - ptot[RT]))
             / (sum(rcy.values()) + _EPS))

    Sx = {"L": SL, "R": SR}
    Sy = {"B": SB, "T": ST}
    rstar_x, rstar_y, rstar = {}, {}, {}
    Astar, Bstar = {}, {}
    Ex_star, Ey_star, E_star = {}, {}, {}
    for k in (LB, RB, LT, RT):
        fx = (Sx[k[0]] - us[k]) / (Sx[k[0]] - ustar
                                   + jnp.where(Sx[k[0]] >= ustar,
                                               _EPS, -_EPS))
        fy = (Sy[k[1]] - vs[k]) / (Sy[k[1]] - vstar
                                   + jnp.where(Sy[k[1]] >= vstar,
                                               _EPS, -_EPS))
        rstar_x[k] = rs[k] * fx
        rstar_y[k] = rs[k] * fy
        rstar[k] = rs[k] * fx * fy
        Bstar[k] = B_of[k[0]] * fx
        Astar[k] = A_of[k[1]] * fy
        Ex_star[k] = ustar * Bstar[k] - vs[k] * A_of[k[1]]
        Ey_star[k] = us[k] * B_of[k[0]] - vstar * Astar[k]
        E_star[k] = ustar * Bstar[k] - vstar * Astar[k]

    def ca_side(keys, field, fstar, rsx):
        out = jnp.full_like(SL, sc)
        for k in keys:
            out = jnp.maximum(out, jnp.abs(field[k[1] if field is A_of
                                                 else k[0]])
                              / jnp.sqrt(jnp.maximum(rsx[k],
                                                     cfg.smallr)))
            out = jnp.maximum(out, jnp.abs(fstar[k])
                              / jnp.sqrt(jnp.maximum(rstar[k],
                                                     cfg.smallr)))
        return out

    caL = ca_side((LB, LT), A_of, Astar, rstar_x)
    caR = ca_side((RB, RT), A_of, Astar, rstar_x)
    caB = ca_side((LB, RB), B_of, Bstar, rstar_y)
    caT = ca_side((LT, RT), B_of, Bstar, rstar_y)
    SAL = jnp.minimum(ustar - caL, 0.0)
    SAR = jnp.maximum(ustar + caR, 0.0)
    SAB = jnp.minimum(vstar - caB, 0.0)
    SAT = jnp.maximum(vstar + caT, 0.0)
    dax = SAR - SAL + _EPS
    day = SAT - SAB + _EPS
    AstarT = (SAR * Astar[RT] - SAL * Astar[LT]) / dax
    AstarB = (SAR * Astar[RB] - SAL * Astar[LB]) / dax
    BstarR = (SAT * Bstar[RT] - SAB * Bstar[RB]) / day
    BstarL = (SAT * Bstar[LT] - SAB * Bstar[LB]) / day

    # supersonic rows/columns
    e_b = jnp.where(SL > 0.0, eps[LB],
                    jnp.where(SR < 0.0, eps[RB],
                              (SAR * Ex_star[LB] - SAL * Ex_star[RB]
                               + SAR * SAL * (B_of["R"] - B_of["L"]))
                              / dax))
    e_t = jnp.where(SL > 0.0, eps[LT],
                    jnp.where(SR < 0.0, eps[RT],
                              (SAR * Ex_star[LT] - SAL * Ex_star[RT]
                               + SAR * SAL * (B_of["R"] - B_of["L"]))
                              / dax))
    e_l = (SAT * Ey_star[LB] - SAB * Ey_star[LT]
           - SAT * SAB * (A_of["T"] - A_of["B"])) / day
    e_r = (SAT * Ey_star[RB] - SAB * Ey_star[RT]
           - SAT * SAB * (A_of["T"] - A_of["B"])) / day
    e_c = ((SAL * SAB * E_star[RT] - SAL * SAT * E_star[RB]
            - SAR * SAB * E_star[LT] + SAR * SAT * E_star[LB])
           / (dax * day)
           - SAT * SAB / day * (AstarT - AstarB)
           + SAR * SAL / dax * (BstarR - BstarL))
    return jnp.where(SB > 0.0, e_b,
                     jnp.where(ST < 0.0, e_t,
                               jnp.where(SL > 0.0, e_l,
                                         jnp.where(SR < 0.0, e_r,
                                                   e_c))))
