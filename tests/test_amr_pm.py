"""Particles on the AMR hierarchy (pm/amr_pm.py + hierarchy wiring).

Oracles:
  * level assignment and CIC deposit bookkeeping against the host tree;
  * mass conservation of the per-level deposits;
  * the degenerate single-level AMR run reproduces the uniform-grid
    coupled stepper (same FFT gravity, same KDK order);
  * refined-hierarchy momentum bookkeeping and decomposition invariance
    on the 8-device mesh (the reference's own multi-rank aggregate trick,
    ``tests/run_test_suite.sh:78-82``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import params_from_string
from ramses_tpu.pm import amr_pm
from ramses_tpu.pm.particles import ParticleSet


def _params(lmin, lmax, ndim=2, refine=""):
    txt = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "poisson=.true.", "pic=.true.", "/",
        "&AMR_PARAMS", f"levelmin={lmin}", f"levelmax={lmax}",
        "boxlen=1.0", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/",
        "&HYDRO_PARAMS", "riemann='hllc'", "courant_factor=0.5", "/",
    ] + ([refine] if refine else []))
    return params_from_string(txt, ndim=ndim)


def _pset(n, ndim, seed=0, vmax=0.1):
    rng = np.random.default_rng(seed)
    return ParticleSet.make(
        rng.uniform(0.05, 0.95, (n, ndim)),
        rng.uniform(-vmax, vmax, (n, ndim)),
        np.full(n, 1.0 / n))


def test_assign_levels_finest_covering():
    p = _params(3, 5, ndim=2,
                refine="&REFINE_PARAMS\nx_refine=0,0,0.25,0.25\n"
                       "y_refine=0,0,0.25,0.25\n"
                       "r_refine=-1,-1,0.15,0.15\n/")
    sim = AmrSim(p, dtype=jnp.float64)
    assert sim.tree.has(5)
    x = np.array([[0.25, 0.25],    # inside the refined ball -> level 5
                  [0.9, 0.9]])     # outside -> base level
    lv = amr_pm.assign_levels(sim.tree, x, 1.0)
    assert lv[0] == 5
    assert lv[1] == 3


def test_deposit_mass_conserved_per_level():
    p = _params(3, 5, ndim=2,
                refine="&REFINE_PARAMS\nx_refine=0,0,0.25,0.25\n"
                       "y_refine=0,0,0.25,0.25\n"
                       "r_refine=-1,-1,0.15,0.15\n/")
    sim = AmrSim(p, dtype=jnp.float64)
    ps = _pset(64, 2, seed=1)
    sim.p = jax.device_put(ps)
    sim.pic = True
    sim._build_pm()
    # base level is complete: every corner lands -> exact total mass
    rho = sim._pm_rho(sim.lmin)
    vol = sim.dx(sim.lmin) ** 2
    m = sim.maps[sim.lmin]
    mass = float(jnp.sum(rho[:m.noct * 4]) * vol)
    assert abs(mass - float(jnp.sum(ps.m))) < 1e-12
    # finer levels: deposited mass <= total (corners outside coverage drop)
    for l in sim.levels():
        if l == sim.lmin:
            continue
        ml = sim.maps[l]
        mass_l = float(jnp.sum(sim._pm_rho(l)[:ml.noct * 4])
                       * sim.dx(l) ** 2)
        assert mass_l <= float(jnp.sum(ps.m)) + 1e-12


def test_degenerate_amr_matches_uniform_pm():
    """lmin=lmax AMR with particles == the uniform coupled stepper."""
    from ramses_tpu.driver import Simulation
    from ramses_tpu.pm.coupling import pm_hydro_step

    lvl, ndim = 4, 2
    ps = _pset(32, ndim, seed=2, vmax=0.05)
    pu = _params(lvl, lvl, ndim=ndim)
    sim = AmrSim(pu, dtype=jnp.float64, particles=jax.device_put(ps))

    uni = Simulation(_params(lvl, lvl, ndim=ndim), dtype=jnp.float64,
                     particles=ps)
    u, p, f = uni.state.u, uni.state.p, uni.state.f
    dt = 1e-3
    for _ in range(3):
        sim.step_coarse(dt)
    dt_old = 0.0
    for _ in range(3):
        u, p, f = pm_hydro_step(uni.grid, uni.gspec, uni.pspec,
                                u, p, f, dt, dt_old)
        dt_old = dt
    xa = np.asarray(sim.p.x)
    xu = np.asarray(p.x)
    # The two steppers are not bit-identical by design: the uniform path
    # feeds the gravity predictor into the MUSCL trace and uses the
    # reference's (-0.5*dt_old old force, +0.5*dt new force) hydro kick
    # split, while the AMR path kicks +-0.5*dt around the sweep with the
    # per-step force.  Both are second order; trajectories agree to
    # O(dt^2 * dphi) — observed ~1e-6 over 3 steps at dt=1e-3.
    np.testing.assert_allclose(xa, xu, atol=1e-5)
    np.testing.assert_allclose(np.asarray(sim.p.v), np.asarray(p.v),
                               atol=1e-4)


@pytest.mark.slow
def test_refined_run_momentum_and_stability():
    """Particles through a refined hierarchy: bounded momentum drift."""
    p = _params(3, 5, ndim=2,
                refine="&REFINE_PARAMS\nx_refine=0,0,0.5,0.5\n"
                       "y_refine=0,0,0.5,0.5\n"
                       "r_refine=-1,-1,0.2,0.2\n/")
    ps = _pset(48, 2, seed=3, vmax=0.05)
    sim = AmrSim(p, dtype=jnp.float64, particles=jax.device_put(ps))
    mom0 = (np.asarray(sim.totals())[1:3]
            + np.asarray(jnp.sum(sim.p.v * sim.p.m[:, None], axis=0)))
    for _ in range(4):
        sim.step_coarse(sim.coarse_dt())
    assert np.all(np.isfinite(np.asarray(sim.p.x)))
    mom1 = (np.asarray(sim.totals())[1:3]
            + np.asarray(jnp.sum(sim.p.v * sim.p.m[:, None], axis=0)))
    # CIC deposit/gather with a shared kernel conserves momentum up to
    # the one-way level interface; drift must stay small
    assert np.all(np.abs(mom1 - mom0) < 2e-3)


def test_sharded_amr_pm_matches_single():
    """Decomposition invariance: 8-device mesh == single device."""
    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

    p = _params(3, 4, ndim=2,
                refine="&REFINE_PARAMS\nx_refine=0,0,0.3\ny_refine=0,0,0.3\n"
                       "r_refine=-1,-1,0.15\n/")
    ps = _pset(32, 2, seed=4, vmax=0.05)
    sim1 = AmrSim(p, dtype=jnp.float64, particles=jax.device_put(ps))
    simN = ShardedAmrSim(p, devices=jax.devices()[:8], dtype=jnp.float64,
                         particles=ps)
    dt = 2e-3
    for _ in range(3):
        sim1.step_coarse(dt)
        simN.step_coarse(dt)
    np.testing.assert_allclose(np.asarray(sim1.p.x),
                               np.asarray(simN.p.x), atol=1e-12)
    for l in sim1.levels():
        np.testing.assert_allclose(np.asarray(sim1.u[l]),
                                   np.asarray(simN.u[l]),
                                   atol=1e-11)


def test_freefall_and_particle_dt_enter_coarse_dt():
    p = _params(4, 4, ndim=2)
    ps = ParticleSet.make(np.array([[0.5, 0.5]]),
                          np.array([[5.0, 0.0]]), np.array([1.0]))
    sim = AmrSim(p, dtype=jnp.float64, particles=jax.device_put(ps))
    dt0 = sim.coarse_dt()
    # particle courant: cf*dx/vmax = 0.5*(1/16)/5
    assert dt0 <= 0.5 * sim.dx(4) / 5.0 + 1e-12
    sim.step_coarse(dt0)
    assert sim._rho_max is not None and sim._rho_max > 0


def test_deposit_schemes_on_hierarchy():
    """NGP/CIC/TSC maps on the AMR hierarchy: each conserves the
    deposited mass exactly (periodic box), with increasing smoothness
    (pm/rho_fine.f90 deposition kernels)."""
    import numpy as np

    from ramses_tpu.amr.tree import Octree
    from ramses_tpu.pm import amr_pm

    rng = np.random.default_rng(5)
    tree = Octree.base(3, 4, 4)
    x = rng.uniform(0, 1, (300, 3))
    m = jnp.asarray(np.full(300, 1.0 / 300))
    act = jnp.ones(300, bool)
    bc = [(0, 0)] * 3
    ncp = {4: 16 ** 3}
    dx = 1.0 / 16
    peaks = {}
    for scheme in ("ngp", "cic", "tsc"):
        maps = amr_pm.build_pm_maps(tree, x, 1.0, bc, ncp,
                                    scheme=scheme)
        mp = maps[4]
        ncorner = {"ngp": 1, "cic": 8, "tsc": 27}[scheme]
        assert mp.idx.shape == (300, ncorner)
        np.testing.assert_allclose(mp.w.sum(axis=1), 1.0, rtol=1e-12)
        rho = amr_pm.deposit_flat(jnp.asarray(mp.idx),
                                  jnp.asarray(mp.w), m, act,
                                  ncp[4], dx ** 3)
        assert np.isclose(float(rho.sum()) * dx ** 3, 1.0, rtol=1e-12)
        peaks[scheme] = float(rho.max())
    # smoother kernels spread mass: NGP peak >= CIC peak >= TSC peak
    assert peaks["ngp"] >= peaks["cic"] >= peaks["tsc"]
