"""Star formation, SN feedback, sinks, and tracers on the AMR hierarchy.

The reference runs these passes per level inside ``amr_step``
(``pm/star_formation.f90:2-954`` called at ``amr/amr_step.f90:369``,
``pm/feedback.f90:472-1029`` thermal_feedback, ``pm/sink_particle.f90``
create/grow/merge, ``pm/move_tracer.f90`` tracer advection).  Here they
run at coarse-step cadence over per-level *flat* cell batches: particle
creation and sink bookkeeping are data-dependent appends — the one
operation that fights XLA's static shapes — so, exactly like the
reference's scalar bookkeeping between vectorized sweeps, they live on
the host, while mass removal/injection transfers back as device arrays.

Level semantics:
  * SF samples only LEAF cells (``star_formation.f90`` runs on active
    grids whose cells have no sons) — covered cells are overwritten by
    restriction anyway;
  * feedback/accretion target the particle's FINEST covering level; the
    containing cell there is a leaf by construction (a refined cell
    would imply a finer covering oct);
  * gas tracers use the flux-probability MC scheme on the hierarchy
    (:func:`mc_tracer_amr`, ``pm/move_tracer.f90``) wherever the fused
    step captures face mass fluxes (hydro family); the MHD hierarchy
    and explicit-comm sharded runs fall back to CIC velocity tracers
    (:func:`tracer_drift_amr`).
"""

from __future__ import annotations

from dataclasses import replace as dreplace

import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr.tree import Octree, map_coords
from ramses_tpu.pm.star_formation import (FLAG_SN_DONE, M_SUN, SfSpec,
                                          append_stars, mstar_quantum,
                                          sf_timescale_code)
from ramses_tpu.units import Units, factG_in_cgs


def ngp_rows(tree: Octree, x: np.ndarray, lvl: int, boxlen: float,
             bc_kinds) -> np.ndarray:
    """Flat cell row of the cell CONTAINING each position at ``lvl``
    (-1 where the level does not cover it) — the NGP analogue of the
    CIC corner maps in :mod:`ramses_tpu.pm.amr_pm`."""
    ndim = tree.ndim
    ttd = 1 << ndim
    dx = boxlen / (1 << lvl)
    cc = np.floor(x / dx).astype(np.int64)
    cc, _ = map_coords(cc, lvl, bc_kinds, ndim)
    og = cc >> 1
    oi = tree.lookup(lvl, og)
    off = np.zeros(len(x), dtype=np.int64)
    for d in range(ndim):
        off = (off << 1) | (cc[:, d] & 1)
    rows = np.where(oi >= 0, oi * ttd + off, -1)
    return rows


def star_formation_amr(sim, dt: float):
    """Schmidt-law SF over every level's leaf cells (coarse cadence).

    Mirrors the uniform pass (``pm/star_formation.py``) on flat batches:
    Poisson-samples N ~ P(mgas/mstar · dt/t_star(ρ)) per eligible leaf
    cell, caps at 90% of the cell gas, removes mass at the cell
    velocity, appends FAM_STAR particles to ``sim.p``.
    """
    spec: SfSpec = sim.sf_spec
    units: Units = sim.units
    nd = sim.cfg.ndim
    ttd = 2 ** nd
    mstar = mstar_quantum(spec, units, sim.dx(sim.lmax), nd)
    rng = sim._sf_rng
    for l in sim.levels():
        m = sim.maps[l]
        ncell = m.noct * ttd
        dx = sim.dx(l)
        vol = dx ** nd
        # fetch the density column only — most levels on a quiet
        # hierarchy have no eligible cell, and the full [ncell, nvar]
        # host copy would dominate the pass
        rho = np.asarray(sim.u[l][:ncell, 0], dtype=np.float64)
        nH = rho * units.scale_nH
        leaf = ~sim.tree.refined_mask(l)
        eligible = leaf & (nH > spec.n_star)
        if not eligible.any():
            continue
        tstar_code = sf_timescale_code(rho, nH, spec, units)
        lam = np.where(eligible, rho * vol / mstar * dt / tstar_code, 0.0)
        cap = np.maximum((0.9 * rho * vol / mstar).astype(np.int64), 0)
        # the draw is capped at 90% of the cell gas anyway; clamping λ
        # there also keeps it inside the Poisson sampler's range (λ→∞
        # would mean converting the whole cell, i.e. the cap)
        lam = np.minimum(np.where(np.isfinite(lam), lam, 0.0), cap)
        big = lam > 1e6             # Poisson(λ)≈λ: deterministic draw
        nnew = np.where(big, lam.astype(np.int64),
                        rng.poisson(np.where(big, 0.0, lam)))
        nnew = np.minimum(nnew, cap)
        rows = np.nonzero(nnew > 0)[0]
        if len(rows) == 0:
            continue
        counts = nnew[rows]
        u = np.array(sim.u[l], dtype=np.float64)
        centers = sim.tree.cell_centers(l, sim.boxlen)[rows]
        vel = u[rows, 1:1 + nd] / np.maximum(u[rows, :1], 1e-300)
        sim.p, sim._next_star_id, kept = append_stars(
            sim.p, centers, vel, counts, mstar, sim.t,
            sim._next_star_id)
        if kept.sum() == 0:
            continue
        dm = kept * mstar / vol
        frac = 1.0 - dm / rho[rows]
        u[rows] *= frac[:, None]
        sim.u[l] = jnp.asarray(u, sim.u[l].dtype)


def thermal_feedback_amr(sim):
    """Delayed thermal SN dumps into each star's finest covering cell
    (``pm/feedback.f90:6-231,351``): stars older than t_sne return
    eta_sn of their mass + 1e51 erg / 10 Msun specific energy, once."""
    from ramses_tpu.pm.amr_pm import assign_levels

    spec: SfSpec = sim.sf_spec
    if spec.eta_sn <= 0:
        return
    from ramses_tpu.pm.star_formation import sn_due_mask

    units: Units = sim.units
    nd = sim.cfg.ndim
    p = sim.p
    due = sn_due_mask(p, spec, units, sim.t)
    if not due.any():
        return
    x = np.asarray(p.x, dtype=np.float64)[due]
    mdue = np.asarray(p.m)[due]
    vstar = np.asarray(p.v)[due]
    mej = spec.eta_sn * mdue
    esn_code = (1e51 / (10.0 * M_SUN)) / units.scale_v ** 2
    lv = assign_levels(sim.tree, x, sim.boxlen)
    for l in sim.levels():
        sel = lv == l
        if not sel.any():
            continue
        rows = ngp_rows(sim.tree, x[sel], l, sim.boxlen, sim.bc_kinds)
        ok = rows >= 0
        if not ok.any():
            continue
        r = rows[ok]
        vol = sim.dx(l) ** nd
        u = np.array(sim.u[l], dtype=np.float64)
        me = mej[sel][ok]
        vs = vstar[sel][ok]
        np.add.at(u[:, 0], r, me / vol)
        for d in range(nd):
            np.add.at(u[:, 1 + d], r, me * vs[:, d] / vol)
        ek = 0.5 * me * (vs ** 2).sum(axis=1)
        np.add.at(u[:, 1 + nd], r, (ek + me * esn_code) / vol)
        sim.u[l] = jnp.asarray(u, sim.u[l].dtype)

    m_arr = np.array(p.m)
    m_arr[due] = m_arr[due] - mej
    flg = np.array(p.flags)
    flg[due] |= FLAG_SN_DONE
    sim.p = dreplace(p, m=jnp.asarray(m_arr), flags=jnp.asarray(flg))


def kinetic_feedback_amr(sim):
    """Delayed KINETIC SN winds on the hierarchy (the ``f_w``
    mass-loaded momentum scheme of ``pm/feedback.f90``; see
    :func:`ramses_tpu.pm.star_formation.kinetic_feedback` for the
    bubble/energy split): the 3^ndim bubble lives on each star's
    finest covering level; bubble cells the level doesn't cover fall
    back to the host cell (their share arrives thermalized there by
    the radial cancellation)."""
    from ramses_tpu.pm.amr_pm import assign_levels
    from ramses_tpu.pm.star_formation import sn_due_mask, wind_shell

    spec: SfSpec = sim.sf_spec
    if spec.eta_sn <= 0:
        return
    units: Units = sim.units
    nd = sim.cfg.ndim
    p = sim.p
    due = sn_due_mask(p, spec, units, sim.t)
    if not due.any():
        return
    x = np.asarray(p.x, dtype=np.float64)[due]
    mej = spec.eta_sn * np.asarray(p.m)[due]
    vstar = np.asarray(p.v)[due]
    esn_code = (1e51 / (10.0 * M_SUN)) / units.scale_v ** 2
    offs, rhat = wind_shell(nd)
    nc = len(offs)
    lv = assign_levels(sim.tree, x, sim.boxlen)
    for l in sim.levels():
        sel = lv == l
        if not sel.any():
            continue
        dxl = sim.dx(l)
        vol = dxl ** nd
        rows0 = ngp_rows(sim.tree, x[sel], l, sim.boxlen, sim.bc_kinds)
        ok = rows0 >= 0
        if not ok.any():
            continue
        u = np.array(sim.u[l], dtype=np.float64)
        r0 = rows0[ok]
        me = mej[sel][ok]
        vs = vstar[sel][ok]
        xs = x[sel][ok]
        # sweep from the host cell (capped at 25% of its gas); SNe
        # sharing a host cell debit it ONCE for their combined draw
        # (fancy-index *= is last-write-wins): group per unique cell
        uniq, inv = np.unique(r0, return_inverse=True)
        mcell_u = u[uniq, 0] * vol
        tot_req = np.bincount(inv, weights=spec.f_w * me)
        tot_allow = np.minimum(tot_req, 0.25 * mcell_u)
        msw = spec.f_w * me * (tot_allow
                               / np.maximum(tot_req, 1e-300))[inv]
        mcell = mcell_u[inv]
        vcell = u[uniq][inv][:, 1:1 + nd] \
            / np.maximum(u[uniq][inv][:, :1], 1e-300)
        e_removed = (msw / np.maximum(mcell, 1e-300)
                     * u[uniq, 1 + nd][inv] * vol)
        u[uniq] *= (1.0 - tot_allow
                    / np.maximum(mcell_u, 1e-300))[:, None]
        mload = me + msw
        vw = np.sqrt(2.0 * esn_code * me / np.maximum(mload, 1e-300))
        vbulk = (me[:, None] * vs + msw[:, None] * vcell) \
            / np.maximum(mload[:, None], 1e-300)
        e_inj = np.zeros(len(me))
        # Bubble targets that are refined at this level are covered by a
        # finer oct: the next restriction sweep overwrites covered cells
        # with son means, silently erasing any deposit.  Treat them like
        # off-level targets (host-cell fallback) so the budget holds
        # across refinement boundaries.
        ref_mask = np.asarray(sim.tree.refined_mask(l))
        for k in range(nc):
            xt = xs + offs[k] * dxl
            rt = ngp_rows(sim.tree, xt, l, sim.boxlen, sim.bc_kinds)
            bad = (rt < 0) | ref_mask[np.maximum(rt, 0)]
            r = np.where(~bad, rt, r0)
            central = np.logical_or(bool((offs[k] == 0).all()), bad)
            mshare = mload / nc
            vk = np.where(central[:, None], vbulk,
                          vbulk + vw[:, None] * rhat[k])
            np.add.at(u[:, 0], r, mshare / vol)
            for d in range(nd):
                np.add.at(u[:, 1 + d], r, mshare * vk[:, d] / vol)
            ek = 0.5 * mshare * (vk ** 2).sum(axis=1)
            np.add.at(u[:, 1 + nd], r, ek / vol)
            e_inj += ek
        # exact budget: the remainder (incl. the off-level fallback
        # shares' suppressed kicks) lands as heat in the host cell
        e_target = (e_removed + me * esn_code
                    + 0.5 * me * (vs ** 2).sum(axis=1))
        np.add.at(u[:, 1 + nd], r0, (e_target - e_inj) / vol)
        sim.u[l] = jnp.asarray(u, sim.u[l].dtype)

    m_arr = np.array(p.m)
    m_arr[due] = m_arr[due] - mej
    flg = np.array(p.flags)
    flg[due] |= FLAG_SN_DONE
    sim.p = dreplace(p, m=jnp.asarray(m_arr), flags=jnp.asarray(flg))


def sink_passes_amr(sim, dt: float):
    """Sink creation/accretion/merging/motion on the hierarchy
    (``pm/sink_particle.f90`` create_sink:6, grow_sink:575,
    accrete_sink:722): threshold creation on leaf cells with an
    exclusion radius, Bondi/threshold accretion from the sink's finest
    covering cell, pairwise merging, leapfrog drift in the AMR gravity
    field (NGP gather at the covering level)."""
    from ramses_tpu.pm.amr_pm import assign_levels
    from ramses_tpu.pm.sinks import SinkSet, merge_sinks

    spec = sim.sink_spec
    units: Units = sim.units
    sinks: SinkSet = sim.sinks
    nd = sim.cfg.ndim
    ttd = 2 ** nd
    gamma = float(sim.cfg.gamma)
    d_thr = spec.n_sink / units.scale_nH

    # ---- creation: leaf cells above n_sink, outside the exclusion radius
    for l in sim.levels():
        if sinks.n >= spec.nsinkmax:
            break
        m = sim.maps[l]
        ncell = m.noct * ttd
        dx = sim.dx(l)
        vol = dx ** nd
        # density column first: quiet levels skip the full host copy
        rho = np.asarray(sim.u[l][:ncell, 0], dtype=np.float64)
        leaf = ~sim.tree.refined_mask(l)
        cand = leaf & (rho * units.scale_nH > spec.n_sink)
        rows = np.nonzero(cand)[0]
        if len(rows) == 0:
            continue
        u = np.array(sim.u[l], dtype=np.float64)
        xnew = sim.tree.cell_centers(l, sim.boxlen)[rows]
        # greedy density-ordered exclusion: the densest candidate wins
        # its merge-radius neighbourhood (the flat-batch stand-in for
        # create_sink's local-maximum test — a resolved clump spawns ONE
        # sink, not one per cell above threshold), also enforced against
        # pre-existing sinks
        order = np.argsort(-rho[rows])
        r2 = (spec.merging_cells * dx) ** 2
        accepted = []
        acc_x = [] if sinks.n == 0 else [sinks.x]
        room = spec.nsinkmax - sinks.n
        for k in order:
            if len(accepted) >= room:
                break
            xs = np.concatenate(acc_x) if acc_x else \
                np.zeros((0, nd))
            if len(xs) and (((xs - xnew[k]) ** 2).sum(-1) < r2).any():
                continue
            accepted.append(k)
            acc_x.append(xnew[k:k + 1])
        if not accepted:
            continue
        accepted = np.asarray(accepted)
        rows, xnew = rows[accepted], xnew[accepted]
        dm_rho = np.maximum(rho[rows] - d_thr, 0.0)
        mnew = dm_rho * vol
        vel = u[rows, 1:1 + nd] / np.maximum(rho[rows, None], 1e-300)
        u[rows] *= (1.0 - dm_rho / rho[rows])[:, None]
        sim.u[l] = jnp.asarray(u, sim.u[l].dtype)
        new_idp = sinks.next_id + np.arange(len(rows), dtype=np.int64)
        stellar = getattr(sim, "stellar", None)
        if stellar is not None:
            for sid, mass in zip(new_idp, mnew):
                stellar.add_accreted(sid, float(mass))
        sinks = SinkSet(
            x=np.concatenate([sinks.x, xnew]),
            v=np.concatenate([sinks.v, vel]),
            m=np.concatenate([sinks.m, mnew]),
            tform=np.concatenate([sinks.tform,
                                  np.full(len(rows), sim.t)]),
            idp=np.concatenate([sinks.idp, new_idp]),
            next_id=sinks.next_id + len(rows))

    # ---- accretion over the sink CLOUD (``create_cloud_from_sink``,
    # pm/sink_particle.f90:131): equal-weight points within
    # 0.5*ir_cloud*dx_min sample the gas state — the Bondi kernel sees
    # the neighbourhood, not one host cell — and the draw distributes
    # over every covered leaf cell with per-cell 90% caps shared
    # between overlapping clouds.
    if sinks.n and spec.accretion_scheme != "none":
        from ramses_tpu.pm.sinks import cloud_offsets
        dxm = sim.dx(max(sim.levels()))
        offs = cloud_offsets(nd, spec.ir_cloud, dxm)
        ncl = len(offs)
        ns = sinks.n
        pts = (sinks.x[:, None, :] + offs[None]).reshape(-1, nd)
        # wrap/clip per dimension: a box periodic in x but walled in z
        # must wrap cloud points through x and clamp them in z
        for d in range(nd):
            if sim.bc_kinds[d] == (0, 0):
                pts[:, d] = np.mod(pts[:, d], sim.boxlen)
            else:
                pts[:, d] = np.clip(pts[:, d], 0.0,
                                    np.nextafter(sim.boxlen, 0))
        lvp = assign_levels(sim.tree, pts, sim.boxlen)
        plvl = np.full(len(pts), -1, dtype=np.int64)
        prow = np.full(len(pts), -1, dtype=np.int64)
        ulv = {}
        vol_l = {l: sim.dx(l) ** nd for l in sim.levels()}
        for l in sim.levels():
            selp = np.nonzero(lvp == l)[0]
            if len(selp) == 0:
                continue
            r = ngp_rows(sim.tree, pts[selp], l, sim.boxlen,
                         sim.bc_kinds)
            ok = r >= 0
            plvl[selp[ok]] = l
            prow[selp[ok]] = r[ok]
            ulv[l] = np.array(sim.u[l], dtype=np.float64)
        valid = plvl >= 0
        npts = len(pts)
        rho_p = np.full(npts, 1e-300)
        mom_p = np.zeros((npts, nd))
        e_p = np.zeros(npts)
        vol_p = np.zeros(npts)
        for l, u in ulv.items():
            m = plvl == l
            rows = prow[m]
            rho_p[m] = np.maximum(u[rows, 0], 1e-300)
            mom_p[m] = u[rows, 1:1 + nd]
            e_p[m] = u[rows, 1 + nd]
            vol_p[m] = vol_l[l]
        # per-sink cloud-averaged state (equal-weight cloud points)
        w2 = valid.reshape(ns, ncl).astype(np.float64)
        wsum = np.maximum(w2.sum(1), 1e-300)
        rho2 = rho_p.reshape(ns, ncl)
        mom2 = mom_p.reshape(ns, ncl, nd)
        # floor-density cells can carry stray momenta whose v=mom/rho
        # overflows f64 — suppress and zero those contributions
        with np.errstate(over="ignore", invalid="ignore",
                         divide="ignore"):
            rho_bar = (rho2 * w2).sum(1) / wsum
            mw = np.maximum((rho2 * w2).sum(1), 1e-300)
            vgas_bar = np.nan_to_num(
                (mom2 * w2[:, :, None]).sum(1) / mw[:, None],
                posinf=0.0, neginf=0.0)
            ek2 = np.nan_to_num(0.5 * (mom2 ** 2).sum(2) / rho2,
                                posinf=0.0, neginf=0.0)
            press2 = (gamma - 1.0) * (e_p.reshape(ns, ncl) - ek2)
            cs2 = gamma * np.maximum((press2 * w2).sum(1) / wsum,
                                     1e-300) \
                / np.maximum(rho_bar, 1e-300)
        if spec.accretion_scheme == "bondi":
            g_code = factG_in_cgs * units.scale_d * units.scale_t ** 2
            vrel2 = ((sinks.v - vgas_bar) ** 2).sum(1)
            mdot = (4 * np.pi * g_code ** 2 * sinks.m ** 2 * rho_bar
                    / np.maximum(cs2 + vrel2, 1e-300) ** 1.5)
            # equal split over the sink's valid cloud points
            dm_p = np.where(valid, np.repeat(mdot * dt / wsum, ncl), 0.0)
        else:   # threshold: per-point excess, deduped per (sink, cell)
            key_sc = (np.repeat(np.arange(ns), ncl) * (1 << 40)
                      + plvl * (1 << 32) + prow)
            _, first = np.unique(np.where(valid, key_sc, -1),
                                 return_index=True)
            once = np.zeros(npts, dtype=bool)
            once[first] = True
            once &= valid
            dm_p = np.where(once, spec.c_acc
                            * np.maximum(rho_p - d_thr, 0.0) * vol_p,
                            0.0)
        # group per unique CELL: cap the combined draw at 90% of gas
        key = plvl * (1 << 48) + prow
        uniq, inv = np.unique(np.where(valid, key, -1),
                              return_inverse=True)
        tot_req = np.bincount(inv, weights=dm_p, minlength=len(uniq))
        # gas available per unique cell (first point of each group)
        firsts = np.zeros(len(uniq), dtype=np.int64)
        firsts[inv[::-1]] = np.arange(npts)[::-1]
        cell_gas = rho_p[firsts] * vol_p[firsts]
        allowed = np.minimum(tot_req, 0.9 * cell_gas)
        scale = allowed / np.maximum(tot_req, 1e-300)
        dm_p = dm_p * scale[inv] * valid
        # write back per level
        with np.errstate(over="ignore", invalid="ignore",
                         divide="ignore"):
            vpt = np.nan_to_num(mom_p / rho_p[:, None],
                                posinf=0.0, neginf=0.0)
        for l, u in ulv.items():
            m = (plvl == l) & (dm_p > 0.0)
            if not m.any():
                continue
            rows = prow[m]
            # additive removal against the PRE-draw state: duplicates
            # (two cloud points in one cell) sum to exactly the
            # combined fraction of the old state — conservative
            np.add.at(u, rows,
                      -u[rows] * (dm_p[m] / (rho_p[m] * vol_l[l]))[:, None])
            sim.u[l] = jnp.asarray(u, sim.u[l].dtype)
        dm = dm_p.reshape(ns, ncl).sum(1)
        p_acc = (vpt * dm_p[:, None]).reshape(ns, ncl, nd).sum(1)
        m_gain = dm
        if spec.agn:
            from ramses_tpu.pm.sinks import agn_energy
            e_agn, m_gain = agn_energy(dm, spec, units)
            # dump into the sink's own covering cell (cloud centre)
            lv0 = assign_levels(sim.tree, sinks.x, sim.boxlen)
            for l in ulv:
                m = lv0 == l
                if not m.any():
                    continue
                rows = ngp_rows(sim.tree, sinks.x[m], l, sim.boxlen,
                                sim.bc_kinds)
                ok = rows >= 0
                u = np.array(sim.u[l], dtype=np.float64)
                np.add.at(u[:, 1 + nd], rows[ok],
                          e_agn[m][ok] / vol_l[l])
                sim.u[l] = jnp.asarray(u, sim.u[l].dtype)
        stellar = getattr(sim, "stellar", None)
        if stellar is not None:
            for sid, dmi in zip(sinks.idp, dm):
                if dmi > 0.0:
                    stellar.add_accreted(sid, float(dmi))
        newm = sinks.m + m_gain
        sinks.v = (sinks.v * sinks.m[:, None] + p_acc) \
            / np.maximum(newm, 1e-300)[:, None]
        sinks.m = newm

    sinks = merge_sinks(sinks, spec, sim.dx(sim.lmax))

    # ---- leapfrog motion in the AMR gravity field
    if sinks.n:
        if sim.gravity and sim.fg:
            lv = assign_levels(sim.tree, sinks.x, sim.boxlen)
            acc = np.zeros_like(sinks.v)
            for l in sim.levels():
                sel = np.nonzero(lv == l)[0]
                if len(sel) == 0 or l not in sim.fg:
                    continue
                rows = ngp_rows(sim.tree, sinks.x[sel], l, sim.boxlen,
                                sim.bc_kinds)
                ok = rows >= 0
                fg = np.asarray(sim.fg[l], dtype=np.float64)
                acc[sel[ok]] = fg[rows[ok]]
            sinks.v = sinks.v + acc * dt
        if spec.direct_force:
            from ramses_tpu.pm.sinks import direct_force_kick
            sinks = direct_force_kick(
                sinks, units, sim.dx(max(sim.levels())), dt,
                sim.boxlen if sim.grav_periodic else None)
        x = sinks.x + sinks.v * dt
        if sim.grav_periodic:
            sinks.x = np.mod(x, sim.boxlen)
        else:
            # open box: sinks leaving the domain are removed (same
            # policy as escaping particles)
            keep = ((x >= 0.0) & (x < sim.boxlen)).all(axis=1)
            sinks = SinkSet(x=x[keep], v=sinks.v[keep], m=sinks.m[keep],
                            tform=sinks.tform[keep], idp=sinks.idp[keep],
                            next_id=sinks.next_id)
    sim.sinks = sinks


def mc_tracer_amr(sim):
    """Flux-probability Monte-Carlo tracer jumps on the hierarchy
    (``pm/move_tracer.f90``, Cadiou+ scheme): a tracer in leaf cell i
    jumps across face f with probability (outgoing mass through f) /
    (cell gas mass before the step), so the tracer distribution follows
    the gas mass distribution exactly in expectation — including across
    refinement boundaries, where the coarse face slots carry the
    flux-correction values (``K.scatter_corr_flux``).

    The fused step captured the coarse step's TOTAL face fluxes per
    level; a level-l cell saw 2^(l-lmin) substeps, so the total
    outgoing probability can exceed 1.  The move therefore runs
    ``R = 2^(lmax-lmin)`` global rounds in which a level-l tracer
    participates at its OWN substep cadence — 2^(l-lmin) moves with
    flux/2^(l-lmin) each, like the reference's per-substep moves (per
    move probability ≤ the CFL number).  Total host work is
    Σ_l 2^(l-lmin)·ntracer(l), linear in the tracer count.

    Known approximations vs ``move_tracer.f90`` (documented on the
    advisor's r04 findings): (1) every substep round divides by the
    PRE-COARSE-STEP density rho0 rather than each substep's own
    pre-step mass — identical to first order in the CFL number,
    biased low in strongly compressive subcycled flows; (2) gas mass
    removed by star formation / sink accretion between flux capture
    and the jump pass is invisible to the probabilities, and gas
    tracers are not converted to star tracers at SF sites (the
    reference's tracer2othertracer); trajectories remain gas-mass
    weighted.
    """
    x = sim.tracer_x
    phi_dev = sim._tracer_phi
    sim._tracer_phi = None
    if x is None or len(x) == 0 or phi_dev is None:
        return
    nd = sim.cfg.ndim
    levels = sim.levels()
    phi = {l: np.asarray(phi_dev[l], dtype=np.float64) for l in phi_dev}
    rho0 = {l: np.asarray(sim._tracer_rho0[l], dtype=np.float64)
            for l in phi}
    rng = sim._tracer_rng
    x = np.asarray(x, dtype=np.float64).copy()
    periodic = all(k == 0 for pair in sim.bc_kinds for k in pair)
    rounds = 1 << (max(levels) - sim.lmin)
    lev = np.full(len(x), -2, dtype=np.int64)
    row = np.full(len(x), -1, dtype=np.int64)
    stale = np.ones(len(x), dtype=bool)        # needs (re)location
    for r in range(rounds):
        # level-l tracers move in rounds r ≡ 0 (mod R/2^(l-lmin))
        active = [l for l in levels
                  if r % (rounds >> (l - sim.lmin)) == 0]
        if stale.any():
            ii0 = np.nonzero(stale)[0]
            xs = x[ii0]
            inbox = ((xs >= 0.0) & (xs < sim.boxlen)).all(axis=1)
            lev[ii0] = -1
            row[ii0] = -1
            for l in levels:
                rr = ngp_rows(sim.tree, xs[inbox], l, sim.boxlen,
                              sim.bc_kinds)
                upd = rr >= 0
                ii = ii0[np.nonzero(inbox)[0][upd]]
                lev[ii] = l        # ascending: finest covering wins
                row[ii] = rr[upd]
            stale[:] = False
        for l in active:
            sel = lev == l
            if not sel.any():
                continue
            nsub = 1 << (l - sim.lmin)
            rows = row[sel]
            mcell = np.maximum(rho0[l][rows], 1e-300)
            ph = phi[l][rows]                      # [n, ndim, 2] signed
            p = np.empty((int(sel.sum()), 2 * nd))
            for d in range(nd):
                p[:, 2 * d] = np.maximum(-ph[:, d, 0], 0.0)   # leave -d
                p[:, 2 * d + 1] = np.maximum(ph[:, d, 1], 0.0)  # leave +d
            p /= (mcell[:, None] * nsub)
            np.clip(p, 0.0, 1.0, out=p)
            c = np.cumsum(p, axis=1)
            uu = rng.random(int(sel.sum()))
            k = (uu[:, None] < c).argmax(axis=1)
            hit = uu < c[:, -1]                    # else: stay
            dxl = sim.dx(l)
            step = np.zeros((int(sel.sum()), nd))
            step[np.arange(len(k)), k // 2] = np.where(k % 2 == 1,
                                                       dxl, -dxl)
            step[~hit] = 0.0
            x[sel] += step
            moved = np.zeros(len(x), dtype=bool)
            moved[np.nonzero(sel)[0][hit]] = True
            stale |= moved
        if periodic:
            x = np.mod(x, sim.boxlen)
    if not periodic:
        keep = ((x >= 0.0) & (x < sim.boxlen)).all(axis=1)
        x = x[keep]
        if getattr(sim, "tracer_id", None) is not None:
            sim.tracer_id = sim.tracer_id[keep]
    sim.tracer_x = x


def tracer_drift_amr(sim, dt: float):
    """Advect passive tracers with the CIC-gathered gas velocity at each
    tracer's finest covering level (velocity-tracer scheme,
    ``pm/move_tracer.f90`` pre-MC path)."""
    from ramses_tpu.pm import amr_pm

    x = sim.tracer_x
    if x is None or len(x) == 0:
        return
    x_host = np.asarray(x, dtype=np.float64)
    ncp = {l: sim.maps[l].ncell_pad for l in sim.levels()}
    maps = amr_pm.build_pm_maps(sim.tree, x_host, sim.boxlen,
                                sim.bc_kinds, ncp)
    nd = sim.cfg.ndim
    v = np.zeros((len(x_host), nd))
    for l, mp in maps.items():
        sel = mp.assigned
        if not sel.any():
            continue
        u = np.array(sim.u[l], dtype=np.float64)
        vel_field = u[:, 1:1 + nd] / np.maximum(u[:, :1], 1e-300)
        vals = np.concatenate([vel_field, np.zeros((1, nd))])[mp.idx]
        gathered = (vals * mp.w[..., None]).sum(axis=1)
        v[sel] = gathered[sel]
    x = x_host + v * dt
    if sim.grav_periodic:
        sim.tracer_x = np.mod(x, sim.boxlen)
    else:
        # open box: tracers leave the domain and are dropped
        keep = ((x >= 0.0) & (x < sim.boxlen)).all(axis=1)
        sim.tracer_x = x[keep]
        if getattr(sim, "tracer_id", None) is not None:
            sim.tracer_id = sim.tracer_id[keep]
