"""Checkpointed adjoint rollouts over the fused uniform step chains.

Reverse-mode AD through a plain ``lax.scan`` of N steps keeps every
intermediate state alive for the backward pass — O(N) memory, which is
exactly the cost profile that makes adjoint CFD impractical on
accelerators.  Here the scan is split into ``outer x inner`` windows with
``jax.checkpoint`` (remat) around each inner window: the forward pass
stores only the ``outer`` window boundaries and recomputes each window of
``inner`` steps during the backward sweep, so peak memory is
O(inner + outer) = O(sqrt(N)) at ``inner = ceil(sqrt(N))`` for ~1 extra
forward pass of compute (cf. Griewank's binomial checkpointing; the JANC
compressible-flow stack, arXiv:2504.13750, uses the same schedule).

The forward pass of :func:`checkpointed_run_steps` is bitwise-identical
to :func:`ramses_tpu.grid.uniform.run_steps` on the XLA path: the step
gating reuses the very same ``cfl_dt``/``step`` callables, and padding
iterations beyond ``nsteps`` are masked with the same ``active`` pattern
(``tests/test_diff.py`` pins this).  The fused Pallas TPU kernel has no
VJP rule, so the differentiable chain always takes the XLA reference path
(which the Pallas kernel is itself pinned bit-identical to).

An EOS gamma can be a *differentiable input*: ``HydroStatic.gamma`` is
normally a static jit cache key, so :func:`rollout` rebuilds the config
with ``dataclasses.replace(cfg, gamma=<traced scalar>)`` inside the
traced function and inlines the XLA step body (every kernel below the
step — pad/ctoprim/slopes/trace/riemann — is a plain function, so the
tracer flows through ``cfg.gamma`` and the derived ``smallp`` floor
transparently).  Note a weak-typing caveat: the traced gamma is cast to
the state dtype so the chain's arithmetic dtype is unchanged.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.grid.uniform import (UniformGrid, _pallas_ok, cfl_dt, step)
from ramses_tpu.hydro import muscl
from ramses_tpu.hydro.timestep import compute_dt


def default_inner(nsteps: int) -> int:
    """sqrt-schedule window length: O(sqrt(N)) adjoint memory."""
    return max(1, int(math.ceil(math.sqrt(max(1, nsteps)))))


def _xla_step(grid: UniformGrid, cfg, u, dt):
    """The XLA reference body of :func:`ramses_tpu.grid.uniform.step` with
    an explicit (possibly gamma-traced) ``cfg``.  Never dispatches to the
    Pallas kernel — it has no VJP rule."""
    dt = jnp.asarray(dt, u.dtype)
    up = bmod.pad(u, grid.bc, cfg, muscl.NGHOST, dx=grid.dx)
    flux, tmp = muscl.unsplit(up, None, dt, (grid.dx,) * cfg.ndim, cfg)
    un = muscl.apply_fluxes(up, flux, cfg)
    if cfg.pressure_fix or cfg.nener:
        un = muscl.dual_energy_fix(up, un, tmp, dt,
                                   (grid.dx,) * cfg.ndim, cfg)
    return bmod.unpad(un, cfg.ndim, muscl.NGHOST)


def _scan_windows(one, carry, nsteps: int, inner: int):
    """outer x inner double scan with remat around each inner window.

    ``one(carry, i)`` advances a single step, masking on the global step
    index ``i`` so the ``outer*inner - nsteps`` padding iterations are
    no-ops (identical masking to the plain driver's ``t < tend`` gate for
    i < nsteps, hence the bitwise pin)."""
    outer = -(-nsteps // inner)

    @jax.checkpoint
    def window(c, idx):
        return jax.lax.scan(one, c, idx)

    idxs = jnp.arange(outer * inner).reshape(outer, inner)
    carry, _ = jax.lax.scan(window, carry, idxs)
    return carry


@partial(jax.jit, static_argnames=("grid", "nsteps", "inner", "dt_scale"))
def checkpointed_run_steps(grid: UniformGrid, u, t, tend, nsteps: int,
                           inner: int | None = None,
                           dt_scale: float = 1.0):
    """Differentiable :func:`ramses_tpu.grid.uniform.run_steps`.

    Same contract — advance up to ``nsteps`` Courant steps, clipped to
    land on ``tend``, returning ``(u, t, ndone)`` — but reverse-mode
    differentiable with O(sqrt(nsteps)) adjoint memory.  The forward pass
    is bitwise-identical to ``run_steps`` on the XLA path (pinned by
    ``tests/test_diff.py``)."""
    if inner is None:
        inner = default_inner(nsteps)
    use_ref = not _pallas_ok(grid, u.dtype)

    def one(carry, i):
        u, t, ndone = carry
        dt = cfl_dt(grid, u) * dt_scale
        dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
        active = (t < tend) & (i < nsteps)
        dt_eff = jnp.where(active, dt, 0.0)
        if use_ref:
            un = step(grid, u, dt_eff)
        else:
            un = _xla_step(grid, grid.cfg, u, dt_eff)
        u = jnp.where(active, un, u)
        t = jnp.where(active, t + dt, t)
        ndone = ndone + jnp.where(active, 1, 0)
        return (u, t, ndone), None

    return _scan_windows(one, (u, t, jnp.array(0)), nsteps, inner)


@partial(jax.jit, static_argnames=("grid", "nsteps", "inner", "dt_scale"))
def _rollout_gamma(grid: UniformGrid, u, t, tend, nsteps: int, gamma,
                   inner: int | None = None, dt_scale: float = 1.0):
    """Checkpointed rollout with a *traced* EOS gamma (see module doc)."""
    if inner is None:
        inner = default_inner(nsteps)
    cfg = dataclasses.replace(grid.cfg, gamma=jnp.asarray(gamma, u.dtype))

    def one(carry, i):
        u, t, ndone = carry
        dt = compute_dt(u, None, grid.dx, cfg) * dt_scale
        dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
        active = (t < tend) & (i < nsteps)
        un = _xla_step(grid, cfg, u, jnp.where(active, dt, 0.0))
        u = jnp.where(active, un, u)
        t = jnp.where(active, t + dt, t)
        ndone = ndone + jnp.where(active, 1, 0)
        return (u, t, ndone), None

    return _scan_windows(one, (u, t, jnp.array(0)), nsteps, inner)


def rollout(grid: UniformGrid, u, t, tend, nsteps: int, gamma=None,
            inner: int | None = None, dt_scale: float = 1.0):
    """Gamma-aware differentiable rollout.

    ``gamma=None`` runs the static-config chain (bitwise pin holds);
    a scalar ``gamma`` (traced or concrete) runs the inlined chain with
    the EOS gamma as a differentiable input."""
    if gamma is None:
        return checkpointed_run_steps(grid, u, t, tend, nsteps,
                                      inner=inner, dt_scale=dt_scale)
    return _rollout_gamma(grid, u, t, tend, nsteps, gamma,
                          inner=inner, dt_scale=dt_scale)


def rollout_loss(theta, u0, target, grid: UniformGrid, t0, tend,
                 nsteps: int, inner: int | None = None,
                 dt_scale: float = 1.0):
    """Scalar data-misfit of a differentiable rollout against ``target``.

    ``theta`` maps parameter names to differentiable overrides:
      ``"u0"``      full initial-state replacement ``[nvar, *sp]``
      ``"du0"``     additive IC perturbation (applied to the base IC)
      ``"ic_scale"``  scalar (or per-channel ``[nvar]``) multiplier on
                    the base IC
      ``"gamma"``   scalar EOS gamma (switches to the traced-gamma chain)
    Returns mean squared error over all cells and channels — the standard
    calibration objective; wrap for anything fancier.
    """
    u = theta.get("u0", u0)
    if "ic_scale" in theta:
        s = jnp.asarray(theta["ic_scale"], u.dtype)
        u = u * (s.reshape((-1,) + (1,) * (u.ndim - 1)) if s.ndim else s)
    if "du0" in theta:
        u = u + theta["du0"]
    uT, _, _ = rollout(grid, u, t0, tend, nsteps,
                       gamma=theta.get("gamma"), inner=inner,
                       dt_scale=dt_scale)
    r = uT - target
    return jnp.mean(r * r)


@partial(jax.jit, static_argnames=("grid", "nsteps", "inner", "dt_scale"))
def rollout_mhd(grid, u, bf, t, tend, nsteps: int,
                inner: int | None = None, dt_scale: float = 1.0):
    """Checkpointed differentiable analog of
    :func:`ramses_tpu.mhd.uniform.run_steps` (CT chain, carry ``(u, bf)``).

    Same sqrt-schedule remat, same ``cfl_dt``/``step`` callables, same
    masking.  Unlike the hydro chain (bitwise-pinned), the CT chain
    matches the plain driver only to ~1 ulp: XLA fuses the step body
    differently under the nested remat scan (t/ndone stay exact;
    ``tests/test_diff.py`` pins the tolerance)."""
    from ramses_tpu.mhd import uniform as mu

    if inner is None:
        inner = default_inner(nsteps)

    def one(carry, i):
        u, bf, t, ndone = carry
        dt = mu.cfl_dt(grid, u, bf) * dt_scale
        dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
        active = (t < tend) & (i < nsteps)
        un, bfn = mu.step(grid, u, bf, jnp.where(active, dt, 0.0))
        u = jnp.where(active, un, u)
        bf = jnp.where(active, bfn, bf)
        t = jnp.where(active, t + dt, t)
        ndone = ndone + jnp.where(active, 1, 0)
        return (u, bf, t, ndone), None

    return _scan_windows(one, (u, bf, t, jnp.array(0)), nsteps, inner)
