"""SED-integrated photon-group properties.

The ``rt/rt_spectra.f90`` role (1,795 LoC there: SED table reading +
group integration): given a source SED (blackbody T_eff here — the
reference's default when no SED file is configured) and group energy
bounds, compute each group's mean photon energy and the
photoionization cross-sections of HI / HeI / HeII averaged over the
group in photon-number weighting (``sigmaN``) and energy weighting
(``sigmaE``) — the quantities the chemistry consumes.

Cross-sections: Verner et al. (1996) analytic fits (the same source
the reference's ``rt_cross_sections`` uses).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence, Tuple

import numpy as np

from ramses_tpu.rt.chem import EV, ION_EV   # shared thresholds/constants
from ramses_tpu.units import kB as KB

H_PLANCK = 6.62607e-27


def _verner(E_eV, E0, s0, ya, P, yw, y0, y1):
    x = E_eV / E0 - y0
    y = np.sqrt(x * x + y1 * y1)
    F = ((x - 1.0) ** 2 + yw * yw) * y ** (0.5 * P - 5.5) \
        * (1.0 + np.sqrt(y / ya)) ** (-P)
    return s0 * 1e-18 * F


def cross_section(E_eV: np.ndarray, species: int) -> np.ndarray:
    """σ(E) [cm²] for species 0=HI, 1=HeI, 2=HeII (Verner+96 Table 1)."""
    E = np.asarray(E_eV, dtype=np.float64)
    if species == 0:
        s = _verner(E, 0.4298, 5.475e4, 32.88, 2.963, 0.0, 0.0, 0.0)
    elif species == 1:
        s = _verner(E, 13.61, 9.492e2, 1.469, 3.188, 2.039, 0.4434, 2.136)
    else:
        s = _verner(E, 1.720, 1.369e4, 32.88, 2.963, 0.0, 0.0, 0.0)
    return np.where(E >= ION_EV[species], s, 0.0)


@dataclass(frozen=True)
class Group3:
    """One photon group's SED-averaged properties (3 species)."""
    e_lo: float                       # eV
    e_hi: float
    e_photon: float                   # mean photon energy, erg
    sigmaN: Tuple[float, float, float]  # cm², number-weighted
    sigmaE: Tuple[float, float, float]  # cm², energy-weighted
    frac: float = 1.0                 # share of the source photon rate


def blackbody_groups(T_eff: float,
                     bounds_eV: Sequence[float]) -> Tuple[Group3, ...]:
    """Integrate a blackbody SED over the group bounds
    (``rt_spectra.f90`` getGroupProps for SED='bb')."""
    raw = []
    for e_lo, e_hi in zip(bounds_eV[:-1], bounds_eV[1:]):
        E = np.linspace(e_lo, min(e_hi, 20.0 * KB * T_eff / EV + e_lo),
                        4096)
        nu = E * EV / H_PLANCK
        x = H_PLANCK * nu / (KB * T_eff)
        bnu = nu ** 3 / np.expm1(np.clip(x, 1e-8, 600.0))
        nphot = bnu / (H_PLANCK * nu)                 # photon-number SED
        wN = np.trapezoid(nphot, nu)
        wE = np.trapezoid(bnu, nu)
        e_mean = wE / max(wN, 1e-300)
        sN, sE = [], []
        for sp in range(3):
            sig = cross_section(E, sp)
            sN.append(np.trapezoid(sig * nphot, nu) / max(wN, 1e-300))
            sE.append(np.trapezoid(sig * bnu, nu) / max(wE, 1e-300))
        raw.append((e_lo, e_hi, float(e_mean),
                    tuple(float(v) for v in sN),
                    tuple(float(v) for v in sE), float(wN)))
    wtot = sum(r[5] for r in raw) or 1.0
    return tuple(Group3(*r[:5], frac=r[5] / wtot) for r in raw)


# the reference's standard 3-group HII/HeII/HeIII setup
DEFAULT_BOUNDS = (13.60, 24.59, 54.42, 1e3)
