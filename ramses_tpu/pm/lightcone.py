"""Cosmological lightcone particle selection.

The geometry core of ``amr/light_cone.f90`` (``perform_my_selection:424``):
between two coarse steps the lightcone shell [r1, r2] (comoving distance
travelled by light) sweeps through periodic replicas of the box; particles
inside the shell are emitted once with their replica-shifted coordinates.
Comoving distances come from the Friedmann conformal-time table the
cosmology module already integrates (r = c·Δτ in supercomoving units).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def shell_radii(cosmo, aexp1: float, aexp2: float) -> Tuple[float, float]:
    """Comoving radii [code units, boxlen=1] of the lightcone shell
    between two expansion factors (observer at aexp=1)."""
    tau1 = float(cosmo.tau_of_aexp(aexp1))
    tau2 = float(cosmo.tau_of_aexp(aexp2))
    tau0 = float(cosmo.tau_of_aexp(1.0 - 1e-12))
    # conformal lookback distance; supercomoving c=... relative scale
    return abs(tau0 - tau2), abs(tau0 - tau1)


def rotation_matrix(thetay: float = 0.0, thetaz: float = 0.0) -> np.ndarray:
    """Observer orientation (``light_cone.f90`` compute_rotation_matrix
    ``:580-640``: a y-rotation by ``thetay`` then a z-rotation by
    ``thetaz`` pointing the cone axis)."""
    cy, sy = np.cos(thetay), np.sin(thetay)
    cz, sz = np.cos(thetaz), np.sin(thetaz)
    ry = np.array([[cy, 0.0, sy], [0.0, 1.0, 0.0], [-sy, 0.0, cy]])
    rz = np.array([[cz, -sz, 0.0], [sz, cz, 0.0], [0.0, 0.0, 1.0]])
    return rz @ ry


def cone_selection(x: np.ndarray, obs: Sequence[float], r1: float,
                   r2: float, boxlen: float = 1.0,
                   opening: Optional[float] = None,
                   axis: Sequence[float] = (0, 0, 1.0),
                   rotation: Optional[np.ndarray] = None):
    """Select particles in the shell r1 <= |x_rep − obs| < r2 over all
    periodic replicas intersecting the shell.

    Returns (positions [m, ndim] in observer coordinates, radii [m],
    source indices [m]) — a particle can appear in several replicas
    (``light_cone.f90`` replica loops).  ``rotation``: optional
    [ndim, ndim] observer orientation (see :func:`rotation_matrix`)
    applied to the emitted coordinates — the narrow-cone frame of
    ``perform_my_selection_narrow``; the opening-angle cut then acts
    along ``axis`` IN THE ROTATED FRAME.
    """
    x = np.asarray(x)
    ndim = x.shape[1]
    obs = np.asarray(obs, dtype=np.float64)
    nrep = int(np.ceil(r2 / boxlen)) + 1
    reps = np.arange(-nrep, nrep + 1) * boxlen
    grids = np.meshgrid(*([reps] * ndim), indexing="ij")
    shifts = np.stack([g.ravel() for g in grids], axis=1)
    # prune replicas whose box cannot intersect the shell
    lo = np.maximum(np.abs(shifts - obs[None, :]) - boxlen, 0.0)
    hi = np.abs(shifts - obs[None, :]) + boxlen
    dmin = np.sqrt((lo ** 2).sum(1))
    dmax = np.sqrt((hi ** 2).sum(1))
    shifts = shifts[(dmax >= r1) & (dmin < r2)]

    out_x, out_r, out_i = [], [], []
    ax = np.asarray(axis, dtype=np.float64)[:ndim]
    ax = ax / np.linalg.norm(ax)
    cos_open = np.cos(opening) if opening is not None else None
    for s in shifts:
        pos = x + s[None, :] - obs[None, :]
        if rotation is not None:
            pos = pos @ np.asarray(rotation).T[:ndim, :ndim]
        r = np.sqrt((pos ** 2).sum(1))
        m = (r >= r1) & (r < r2)
        if cos_open is not None:
            mu = (pos @ ax) / np.maximum(r, 1e-300)
            m &= mu >= cos_open
        if m.any():
            out_x.append(pos[m])
            out_r.append(r[m])
            out_i.append(np.where(m)[0])
    if not out_x:
        return (np.zeros((0, ndim)), np.zeros(0),
                np.zeros(0, dtype=np.int64))
    return (np.concatenate(out_x), np.concatenate(out_r),
            np.concatenate(out_i))


def write_cone(path: str, pos: np.ndarray, r: np.ndarray,
               idx: np.ndarray, aexp: float) -> None:
    """Cone dump (``output_cone`` reduced to an npz payload)."""
    np.savez_compressed(path, pos=pos, r=r, idx=idx, aexp=aexp)
