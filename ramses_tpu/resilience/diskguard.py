"""Graceful degradation under disk pressure.

A fleet worker's natural death mode on a filling disk is an unhandled
``OSError(ENOSPC)`` out of the fsync-heavy checkpoint/commit paths —
the worker crashes, the job bounces, the next worker crashes on the
same disk.  The guard turns that into staged degradation:

* below the **soft** free-space watermark the per-chunk checkpoint
  beat is *shed* (the run keeps stepping, resumability gets coarser,
  an ``io_degraded`` telemetry event + Prometheus gauge say so);
* below the **hard** watermark the serve worker additionally *stops
  claiming new jobs* — it stays alive, finishes what it holds,
  heartbeats (zero-byte mtime fallback exists for ENOSPC), and
  resumes claiming the moment space returns;
* an actual ``ENOSPC`` raised inside a guarded write is absorbed
  (:func:`guarded_save`): the checkpoint is skipped, the guard holds
  itself at least soft-degraded for a cooldown, and the worker lives.

Watermarks come from ``&ENSEMBLE_PARAMS disk_soft_free_mb`` /
``disk_hard_free_mb`` (per-job) or the ``RAMSES_DISK_SOFT_MB`` /
``RAMSES_DISK_HARD_MB`` env vars (per-worker; env wins).  ``0``
disables a watermark.  Stdlib-only; the probe is injectable so tests
never need to actually fill a disk.
"""

from __future__ import annotations

import errno
import os
import time
from typing import Callable, Optional

ENV_SOFT = "RAMSES_DISK_SOFT_MB"
ENV_HARD = "RAMSES_DISK_HARD_MB"

_MB = 1024.0 * 1024.0

#: degradation levels, in increasing severity
LEVELS = ("ok", "soft", "hard")


def free_bytes(path: str) -> float:
    """Free bytes available to this process on ``path``'s filesystem
    (0.0 when even statvfs fails — a dead filesystem is maximally
    degraded, not a crash)."""
    try:
        st = os.statvfs(path)
        return float(st.f_bavail) * float(st.f_frsize)
    except OSError:
        return 0.0


def is_enospc(err: BaseException) -> bool:
    return isinstance(err, OSError) and err.errno == errno.ENOSPC


def _env_mb(name: str, fallback: float) -> float:
    try:
        raw = os.environ.get(name)
        return float(raw) if raw not in (None, "") else float(fallback)
    except (TypeError, ValueError):
        return float(fallback)


class DiskGuard:
    """Free-space watermark over one directory.  ``probe`` is the
    free-bytes function (injectable for tests and fault drills);
    ``cooldown_s`` is how long an observed ENOSPC keeps the guard at
    least soft-degraded even if the probe claims space (quota errors
    and statvfs lag both look like that)."""

    def __init__(self, path: str, soft_free_bytes: float = 0.0,
                 hard_free_bytes: float = 0.0,
                 probe: Optional[Callable[[str], float]] = None,
                 cooldown_s: float = 60.0, log=None):
        self.path = path
        self.soft = max(0.0, float(soft_free_bytes))
        self.hard = max(0.0, float(hard_free_bytes))
        self._probe = probe or free_bytes
        self._cooldown_s = float(cooldown_s)
        self._enospc_until = 0.0       # monotonic deadline
        self._last_emitted = "ok"      # transition-edge event dedup
        self._log = log

    @classmethod
    def from_env(cls, path: str, log=None) -> "DiskGuard":
        """Worker-level guard: env watermarks only."""
        return cls(path, soft_free_bytes=_env_mb(ENV_SOFT, 0.0) * _MB,
                   hard_free_bytes=_env_mb(ENV_HARD, 0.0) * _MB,
                   log=log)

    @classmethod
    def from_params(cls, params, path: str, log=None) -> "DiskGuard":
        """Per-job guard: ``&ENSEMBLE_PARAMS`` watermarks, env
        override."""
        ens = getattr(params, "ensemble", None)
        soft = float(getattr(ens, "disk_soft_free_mb", 0.0) or 0.0)
        hard = float(getattr(ens, "disk_hard_free_mb", 0.0) or 0.0)
        return cls(path,
                   soft_free_bytes=_env_mb(ENV_SOFT, soft) * _MB,
                   hard_free_bytes=_env_mb(ENV_HARD, hard) * _MB,
                   log=log)

    def free_bytes(self) -> float:
        return float(self._probe(self.path))

    def level(self) -> str:
        """Current degradation level; an ENOSPC cooldown clamps to at
        least ``soft`` regardless of what the probe says."""
        free = self.free_bytes()
        lvl = "ok"
        if self.hard > 0.0 and free < self.hard:
            lvl = "hard"
        elif self.soft > 0.0 and free < self.soft:
            lvl = "soft"
        if lvl == "ok" and time.monotonic() < self._enospc_until:
            lvl = "soft"
        return lvl

    def allow_checkpoint(self) -> bool:
        """Shed checkpoint rotation first — below soft nothing new is
        written to disk by the beat."""
        return self.level() == "ok"

    def allow_claim(self) -> bool:
        """Stop claiming only at hard pressure — a soft-degraded
        worker still drains the queue."""
        return self.level() != "hard"

    def note_enospc(self) -> None:
        """An ENOSPC escaped a guarded write: hold degraded for the
        cooldown window."""
        self._enospc_until = time.monotonic() + self._cooldown_s

    def emit(self, telemetry=None, where: str = "") -> str:
        """Emit an ``io_degraded`` event on level *transitions* (both
        directions — recovery is an event too).  Returns the level."""
        lvl = self.level()
        if lvl == self._last_emitted:
            return lvl
        self._last_emitted = lvl
        free = self.free_bytes()
        if self._log is not None:
            self._log(f"diskguard: {where or self.path} -> {lvl} "
                      f"({free / _MB:.0f} MiB free)")
        if telemetry is not None:
            try:
                telemetry.record_event(
                    "io_degraded", level=lvl, where=where,
                    free_bytes=int(free),
                    soft_bytes=int(self.soft),
                    hard_bytes=int(self.hard))
            except Exception:
                pass
        return lvl


def guarded_save(save_fn: Callable[[], None],
                 guard: Optional[DiskGuard], telemetry=None,
                 log=None, where: str = "checkpoint") -> bool:
    """Run an ENOSPC-prone checkpoint write under the watermark:
    skipped outright when the guard is already degraded, and an
    ``ENOSPC`` raised inside degrades (note + skip + event) instead of
    crashing the worker.  Every other exception propagates untouched.
    Returns True when the write actually ran."""
    if guard is not None and not guard.allow_checkpoint():
        guard.emit(telemetry, where=where)
        return False
    try:
        save_fn()
        if guard is not None:
            guard.emit(telemetry, where=where)   # recovery edge
        return True
    except OSError as e:
        if not is_enospc(e):
            raise
        if guard is not None:
            guard.note_enospc()
            guard.emit(telemetry, where=where)
        if log is not None:
            log(f"diskguard: ENOSPC during {where} — checkpoint "
                f"shed, worker continues")
        if telemetry is not None:
            try:
                telemetry.record_event("io_degraded", level="enospc",
                                       where=where, free_bytes=0)
            except Exception:
                pass
        return False
