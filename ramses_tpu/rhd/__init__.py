"""Special-relativistic hydrodynamics (SURVEY.md §2.4).

The ``SOLVER=rhd`` build (Lamberts+2013): conservative (D, S, τ) state,
Newton conservative→primitive recovery, ideal and Taub-Mathews equations
of state, relativistic HLL fluxes, Lorentz-factor refinement criterion.
"""
