"""Batched ensemble engine: one compiled program, a fleet of runs.

ROADMAP item 3 (and JANC, arXiv:2504.13750, as the existence proof):
production scale for this framework is *many* simulations, so the
fused uniform step chains (``grid/uniform.run_steps``/``run_steps_cool``,
``mhd/uniform.run_steps``, ``rhd/uniform.run_steps``) are vmapped over a
leading member axis.  :class:`EnsembleSpec` expands one base namelist
into N members by sweeping parameters; anything *traced* (region
densities/pressures, IC perturbation seeds, cooling table data) batches
freely inside one compiled program, while sweeps that touch a *static*
config field (EOS gamma, the Riemann solver, a CoolingSpec knob) change
the frozen dataclass that IS the jit cache key — those members are
grouped into sub-batches by frozen-config hash so each distinct config
compiles exactly once (``platform.enable_compile_cache`` makes even that
cold-start O(load) for a known namelist).

Per-member time is carried as a batched ``t[B]`` array and completion is
the per-step ``t < tend`` mask already inside every ``run_steps`` scan —
under vmap it becomes a per-member ``lax.select``, so finished members
idle cheaply until their sub-batch drains.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import time
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.config import Params

_INDEXED = re.compile(r"^(?P<name>\w+)\[(?P<idx>\d+)\]$")

#: round-off slack shared with the drivers' "reached tend" checks
_TEND_EPS = 1e-12


def apply_override(params: Params, key: str, value: Any) -> None:
    """Set a dotted sweep path (``"hydro.gamma"``, ``"init.p_region[1]"``)
    on a :class:`Params` in place.  Unknown groups/fields raise — a
    silently ignored sweep would make every member identical."""
    group, _, fname = key.partition(".")
    if not fname:
        raise ValueError(f"sweep key '{key}' is not of the form "
                         "'group.field' or 'group.field[i]'")
    sub = getattr(params, group)
    m = _INDEXED.match(fname)
    if m:
        lst = list(getattr(sub, m.group("name")))
        lst[int(m.group("idx"))] = value
        setattr(sub, m.group("name"), lst)
    else:
        cur = getattr(sub, fname)          # AttributeError when unknown
        if isinstance(cur, bool):
            value = bool(value)
        elif isinstance(cur, int) and not isinstance(value, bool):
            value = int(value)
        elif isinstance(cur, float):
            value = float(value)
        setattr(sub, fname, value)


def solver_from_params(params: Params) -> str:
    """Solver-family auto-detect shared with ``__main__``: MHD when any
    region seeds a magnetic field, hydro otherwise (rhd is explicit)."""
    init = params.init
    return ("mhd" if any(init.A_region) or any(init.B_region)
            or any(init.C_region) else "hydro")


@dataclass
class EnsembleSpec:
    """One base namelist + per-member parameter sweeps.

    ``sweeps`` maps dotted parameter paths to per-member value lists
    (every list must have length ``nmember``).  ``perturb_amp > 0``
    additionally multiplies each member's IC density by
    ``1 + amp * U[-1, 1)`` drawn from ``default_rng(perturb_seed + k)``
    — a traced-only sweep that never splits the jit cache.
    """
    base: Params
    nmember: int
    sweeps: Dict[str, List[Any]] = field(default_factory=dict)
    perturb_amp: float = 0.0
    perturb_seed: int = 0
    solver: str = ""               # "" -> auto (hydro/mhd)

    def __post_init__(self):
        if self.nmember < 1:
            raise ValueError(f"nmember must be >= 1 (got {self.nmember})")
        if not self.solver:
            self.solver = solver_from_params(self.base)
        for key, vals in self.sweeps.items():
            if len(vals) != self.nmember:
                raise ValueError(
                    f"sweep '{key}' has {len(vals)} values for "
                    f"{self.nmember} members")

    @classmethod
    def from_params(cls, params: Params,
                    sweeps: Optional[Dict[str, Sequence[Any]]] = None,
                    nmember: Optional[int] = None,
                    solver: str = "") -> "EnsembleSpec":
        """Build from ``&ENSEMBLE_PARAMS`` (plus optional explicit
        sweeps, e.g. from a queue job record).  Namelist ``sweep_name``
        rows ramp linearly ``sweep_start -> sweep_stop`` across the
        members; explicit ``sweeps`` win on key collision."""
        e = params.ensemble
        sweeps = {k: list(v) for k, v in (sweeps or {}).items()}
        nm = int(nmember or 0) or int(e.nmember) or \
            (max(len(v) for v in sweeps.values()) if sweeps else 1)
        for i, name in enumerate(e.sweep_name):
            if name in sweeps:
                continue
            lo = float(e.sweep_start[i]) if i < len(e.sweep_start) else 0.0
            hi = float(e.sweep_stop[i]) if i < len(e.sweep_stop) else lo
            sweeps[name] = [lo + (hi - lo) * (k / (nm - 1) if nm > 1
                                              else 0.0)
                            for k in range(nm)]
        return cls(base=params, nmember=nm, sweeps=sweeps,
                   perturb_amp=float(e.perturb_amp),
                   perturb_seed=int(e.perturb_seed), solver=solver)

    def member_params(self, k: int) -> Params:
        """Member k's full Params (a private copy with its sweeps
        applied).  The clone goes through a pickle round-trip with the
        serialized base cached on first use — ~6x cheaper than
        ``copy.deepcopy`` and paid once per member when expanding a
        batch, so it dominates small-job engine construction.  Mutating
        ``self.base`` after the first call is not supported."""
        if not 0 <= k < self.nmember:
            raise IndexError(k)
        blob = self.__dict__.get("_base_blob")
        if blob is None:
            blob = pickle.dumps(self.base, pickle.HIGHEST_PROTOCOL)
            self.__dict__["_base_blob"] = blob
        p = pickle.loads(blob)
        for key, vals in self.sweeps.items():
            apply_override(p, key, vals[k])
        return p

    def fingerprint(self) -> str:
        """Stable id of the expansion (checkpoint compatibility check)."""
        blob = json.dumps({"nmember": self.nmember, "solver": self.solver,
                           "sweeps": {k: [repr(v) for v in vs]
                                      for k, vs in sorted(self.sweeps.items())},
                           "perturb": [self.perturb_amp, self.perturb_seed]},
                          sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _uniform_shape(p: Params, cubic: bool) -> Tuple[Tuple[int, ...], float]:
    n = 2 ** p.amr.levelmin
    base = [p.amr.nx, p.amr.ny, p.amr.nz][:p.ndim]
    if cubic and any(b != 1 for b in base):
        raise NotImplementedError(
            f"{'mhd/rhd'} ensembles require nx=ny=nz=1 (got {base})")
    shape = tuple(b * n for b in base)
    return shape, p.amr.boxlen / n


def _check_uniform_only(p: Params, solver: str) -> None:
    if p.amr.levelmax > p.amr.levelmin:
        raise NotImplementedError(
            "ensemble engine covers the uniform fused step chains only "
            f"(levelmin={p.amr.levelmin} < levelmax={p.amr.levelmax}); "
            "run AMR namelists solo")
    r = p.run
    if r.poisson or r.pic or r.cosmo or r.rt:
        raise NotImplementedError(
            "ensemble engine: pure (M/R)HD uniform runs only — "
            "poisson/pic/cosmo/rt namelists run solo")
    if solver == "hydro" and p.run.patch:
        # patch hooks are process-global state; per-member patches
        # cannot coexist inside one batch
        raise NotImplementedError("ensemble engine does not support "
                                  "&RUN_PARAMS patch plug-ins")


def _perturb(u0: np.ndarray, spec: EnsembleSpec, k: int) -> np.ndarray:
    if spec.perturb_amp <= 0.0:
        return u0
    rng = np.random.default_rng(spec.perturb_seed + k)
    u0 = np.array(u0, copy=True)
    u0[0] = u0[0] * (1.0 + spec.perturb_amp
                     * (2.0 * rng.random(u0[0].shape) - 1.0))
    return u0


def build_member(spec: EnsembleSpec, k: int, dtype=jnp.float64):
    """(grid, state, tend, params) for member k — the single source of
    truth for ICs, shared by the engine and by bitwise solo-run tests.

    ``state`` is a tuple of device arrays: ``(u,)`` for hydro/rhd,
    ``(u, bf)`` for MHD.  ``grid`` is the frozen static dataclass that
    doubles as the jit cache key (and the sub-batch group key)."""
    from ramses_tpu.grid import boundary as bmod

    # no-sweep fast path: every member shares one (grid, ICs, params)
    # template — cached on the spec — and differs only by the traced
    # perturbation, so an N-member expansion builds the grid and runs
    # condinit once instead of N times (this dominates small-job engine
    # construction).  The shared ``p`` is the same object for every
    # member; callers treat it as read-only.
    tmpl = (spec.__dict__.get("_member_template")
            if not spec.sweeps else None)
    if tmpl is not None and spec.solver == "hydro":
        grid, u0, tend, p = tmpl
        u0k = _perturb(u0, spec, k)
        return grid, (jnp.asarray(u0k, dtype),), tend, p

    p = spec.member_params(k)
    _check_uniform_only(p, spec.solver)
    tend = float(p.output.tout[-1] if p.output.tout else p.output.tend)
    if spec.solver == "hydro":
        from ramses_tpu.grid.uniform import UniformGrid
        from ramses_tpu.hydro.core import HydroStatic
        from ramses_tpu.init.regions import condinit
        cfg = HydroStatic.from_params(p)
        shape, dx = _uniform_shape(p, cubic=False)
        grid = UniformGrid(cfg=cfg, shape=shape, dx=dx,
                           bc=bmod.BoundarySpec.from_params(p))
        u0 = np.asarray(condinit(shape, dx, p, cfg))
        if not spec.sweeps:
            spec.__dict__["_member_template"] = (grid, u0, tend, p)
        u0k = _perturb(u0, spec, k)
        return grid, (jnp.asarray(u0k, dtype),), tend, p
    if spec.solver == "mhd":
        from ramses_tpu.mhd.driver import mhd_condinit
        from ramses_tpu.mhd.core import MhdStatic
        from ramses_tpu.mhd import uniform as mu
        cfg = MhdStatic.from_params(p)
        shape, dx = _uniform_shape(p, cubic=True)
        spec_bc = bmod.BoundarySpec.from_params(p)
        bc_kinds = tuple((f[0].kind, f[1].kind) for f in spec_bc.faces)
        for lo, hi in bc_kinds:
            for kk in (lo, hi):
                if kk not in (bmod.PERIODIC, bmod.OUTFLOW):
                    raise NotImplementedError(
                        "mhd ensembles: periodic/outflow only")
        grid = mu.MhdGrid(cfg=cfg, shape=shape, dx=dx, bc_kinds=bc_kinds)
        u0, bf0 = mhd_condinit(shape, dx, p, cfg)
        u0 = _perturb(np.asarray(u0), spec, k)
        return grid, (jnp.asarray(u0, dtype),
                      jnp.asarray(bf0, dtype)), tend, p
    if spec.solver == "rhd":
        from ramses_tpu.rhd.driver import rhd_condinit
        from ramses_tpu.rhd.core import RhdStatic
        from ramses_tpu.rhd import uniform as ru
        cfg = RhdStatic.from_params(p)
        shape, dx = _uniform_shape(p, cubic=True)
        spec_bc = bmod.BoundarySpec.from_params(p)
        bc_kinds = tuple((f[0].kind, f[1].kind) for f in spec_bc.faces)
        for lo, hi in bc_kinds:
            for kk in (lo, hi):
                if kk not in (bmod.PERIODIC, bmod.OUTFLOW):
                    raise NotImplementedError(
                        "rhd ensembles: periodic/outflow only")
        grid = ru.RhdGrid(cfg=cfg, shape=shape, dx=dx, bc_kinds=bc_kinds)
        u0 = _perturb(np.asarray(rhd_condinit(shape, dx, p, cfg)), spec, k)
        return grid, (jnp.asarray(u0, dtype),), tend, p
    raise ValueError(f"unknown solver '{spec.solver}'")


def member_cooling(p: Params):
    """(tables, cspec) for a member's &COOLING_PARAMS, or (None, None).
    Table *data* is traced (J21 sweeps batch freely); ``cspec`` is the
    frozen static part that splits the sub-batch grouping."""
    if not p.cooling.cooling:
        return None, None
    from ramses_tpu.hydro.cooling import CoolingSpec, build_tables
    from ramses_tpu.units import units as units_fn
    cspec = CoolingSpec.from_params(p, units_fn(p, cosmo=None, aexp=1.0))
    c = p.cooling
    tables = build_tables(aexp=1.0, J21=float(c.J21),
                          a_spec=float(c.a_spec),
                          z_reion=float(c.z_reion),
                          haardt_madau=bool(c.haardt_madau))
    return tables, cspec


@dataclass
class SubBatch:
    """One frozen-config group: members that share a jit cache key."""
    grid: Any
    cspec: Any                       # cooling static part (hydro only)
    members: List[int]               # member indices, batch order
    state: Tuple[Any, ...]           # each [B, ...]
    tables: Any                      # stacked cooling tables or None
    t: Any                           # [B] device
    tend: np.ndarray                 # [B] host
    nstep: np.ndarray                # [B] host, real steps done
    t_host: np.ndarray               # [B] host mirror of t (refreshed
    #                                  by the per-dispatch fetch)
    quarantined: np.ndarray          # [B] host bool (evicted members)
    replicas: int = 1                # packed-mode replica count (the
    #                                  member axis shards over this
    #                                  many devices; 1 = single-device)

    @property
    def size(self) -> int:
        return len(self.members)


class EnsembleEngine:
    """Advance every member of an :class:`EnsembleSpec` to its tend.

    Members are grouped by ``(grid, cspec)`` — the frozen static
    dataclasses that are the jit cache keys — so each distinct config
    compiles once and a traced-only sweep compiles exactly once total.
    The drive loop dispatches fused ``chunk_steps``-step windows per
    group until all members complete (per-member ``tend`` or
    ``&RUN_PARAMS nstepmax``).
    """

    def __init__(self, spec: EnsembleSpec, dtype=jnp.float64,
                 telemetry=None, plan=None):
        from ramses_tpu.ensemble.meshplan import MeshPlan
        from ramses_tpu.telemetry import make_telemetry
        self.spec = spec
        self.params = spec.base
        self.dtype = dtype
        #: two-level packing (ensemble/meshplan): how this job's
        #: sub-batches land on the assigned devices
        self.plan = plan if plan is not None else MeshPlan.single()
        self._slab_mesh = None
        # checkpoint dirty-tracking: save() skips the rewrite when no
        # step has landed since the last snapshot (run_job's final save
        # immediately after the last on_chunk beat is otherwise a full
        # redundant checkpoint — measurable per-job cost for small jobs)
        self._dirty = True
        self._last_snap = ""
        tdt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        by_key: Dict[Any, Dict[str, list]] = {}
        for k in range(spec.nmember):
            grid, state, tend, p = build_member(spec, k, dtype=dtype)
            tables, cspec = (member_cooling(p) if spec.solver == "hydro"
                             else (None, None))
            g = by_key.setdefault((grid, cspec), dict(
                grid=grid, cspec=cspec, members=[], states=[],
                tables=[], tend=[]))
            g["members"].append(k)
            g["states"].append(state)
            g["tables"].append(tables)
            g["tend"].append(tend)
        self.groups: List[SubBatch] = []
        for g in by_key.values():
            ncomp = len(g["states"][0])
            state = tuple(jnp.stack([s[c] for s in g["states"]])
                          for c in range(ncomp))
            tables = (jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *g["tables"])
                if g["tables"][0] is not None else None)
            b = len(g["members"])
            self.groups.append(SubBatch(
                grid=g["grid"], cspec=g["cspec"], members=g["members"],
                state=state, tables=tables, t=jnp.zeros(b, tdt),
                tend=np.asarray(g["tend"], np.float64),
                nstep=np.zeros(b, np.int64),
                t_host=np.zeros(b, np.float64),
                quarantined=np.zeros(b, bool)))
        self.wall_s = 0.0
        self.cell_updates = 0
        self._iout = 0
        #: member isolation ladder state: {member: {reason, nstep, t,
        #: dump}} for members evicted by the batched step-guard
        self.quarantined: Dict[int, Dict[str, Any]] = {}
        #: correlation fields (trace_id/job/worker — ramses_tpu/obs)
        #: the serve loop sets after construction; folded into every
        #: checkpoint manifest meta so artifacts join the job's trace
        self.trace_meta: Dict[str, Any] = {}
        self.telemetry = (telemetry if telemetry is not None
                          else make_telemetry(spec.base,
                                              run_info=self.run_info()))
        from ramses_tpu.resilience.faultinject import FaultInjector
        from ramses_tpu.resilience.stepguard import BatchGuard
        self._bguard = BatchGuard.from_params(spec.base,
                                              telemetry=self.telemetry)
        self._fault = FaultInjector.from_params(spec.base)
        # hang watchdog: &ENSEMBLE_PARAMS *_deadline_s (None when off)
        from ramses_tpu.resilience.watchdog import Watchdog
        self._wd = Watchdog.from_params(spec.base, scope="ensemble",
                                        telemetry=self.telemetry)
        if self.plan.mode == "slab":
            from ramses_tpu.parallel import halo
            if spec.solver != "hydro" or any(g.tables is not None
                                             for g in self.groups):
                raise NotImplementedError(
                    "slab-mode ensembles: pure hydro without cooling "
                    "only (parallel/halo pipeline scope)")
            if self._bguard is not None:
                raise NotImplementedError(
                    "slab-mode ensembles do not support the batched "
                    "step-guard (run_steps_halo has no summarize/"
                    "dt_scale surface); disable &RESILIENCE_PARAMS "
                    "step_guard or run packed/single")
            self._slab_mesh = halo.make_halo_mesh(self.plan.devices())
            for g in self.groups:
                halo._check(g.grid, self._slab_mesh)
        elif self.plan.mode == "packed":
            for g in self.groups:
                self._place_group(g)

    def _place_group(self, g: SubBatch) -> None:
        """Packed-mode placement: shard one sub-batch's member axis
        over the replica mesh.  The replica count is the largest
        divisor of the batch size within the assigned device count
        (NamedSharding needs an even split — and an even split keeps
        the per-device replica programs identical, which is what makes
        packed execution bitwise-equal to single-device).  Called at
        construction and again after a checkpoint load, so a
        checkpoint written under any packing restores under any
        other."""
        if self.plan.mode != "packed":
            return
        from ramses_tpu.ensemble.meshplan import largest_divisor
        from ramses_tpu.parallel.mesh import (replica_mesh,
                                              replica_sharding)
        devs = self.plan.devices()
        cap = int(self.plan.max_replicas) or len(devs)
        r = largest_divisor(g.size, min(cap, len(devs)))
        g.replicas = r
        if r <= 1:
            return
        mesh = replica_mesh(devs[:r])
        g.state = tuple(
            jax.device_put(c, replica_sharding(mesh, c.ndim))
            for c in g.state)
        g.t = jax.device_put(g.t, replica_sharding(mesh, 1))
        if g.tables is not None:
            g.tables = jax.tree_util.tree_map(
                lambda x: jax.device_put(
                    x, replica_sharding(mesh, x.ndim)), g.tables)

    # ------------------------------------------------------------------
    # status surface (duck-typed like the solo sims, for the supervisor,
    # telemetry close() and OpsGuard-style callers)
    @property
    def nmember(self) -> int:
        return self.spec.nmember

    @property
    def t(self) -> float:
        """Least-advanced *healthy* member time (monotone; tend when
        all done).  Host-cached — no device fetch."""
        vals = [float(g.t_host[~g.quarantined].min())
                for g in self.groups if (~g.quarantined).any()]
        if not vals:                   # everything quarantined
            vals = [float(g.t_host.min()) for g in self.groups]
        return float(min(vals))

    @property
    def nstep(self) -> int:
        """Largest member step count (monotone checkpoint ordinal)."""
        return int(max(int(g.nstep.max()) for g in self.groups))

    @property
    def quarantined_count(self) -> int:
        """Members evicted by the member isolation ladder (telemetry
        folds this into step/chunk records)."""
        return len(self.quarantined)

    def run_info(self) -> Dict[str, Any]:
        info = {"driver": f"ensemble-{self.spec.solver}"
                if hasattr(self, "spec") else "ensemble",
                "nmember": self.spec.nmember,
                "ngroup": len(getattr(self, "groups", [])),
                "sweeps": sorted(self.spec.sweeps)}
        plan = getattr(self, "plan", None)
        if plan is not None:
            info["packing"] = plan.describe()
            groups = getattr(self, "groups", None)
            if groups:
                info["packing"]["group_replicas"] = [
                    int(g.replicas) for g in groups]
        return info

    def _member_pos(self, k: int) -> Tuple[SubBatch, int]:
        for g in self.groups:
            if k in g.members:
                return g, g.members.index(k)
        raise IndexError(k)

    def member_state(self, k: int) -> Dict[str, Any]:
        """Member k's current state: ``u`` (+ ``bf`` for MHD), t, nstep."""
        g, i = self._member_pos(k)
        out = {"u": g.state[0][i], "t": float(np.asarray(g.t)[i]),
               "nstep": int(g.nstep[i]),
               "quarantined": bool(g.quarantined[i])}
        if len(g.state) > 1:
            out["bf"] = g.state[1][i]
        return out

    def _group_done(self, g: SubBatch, nstepmax: int) -> np.ndarray:
        """Per-member completion from host-cached time: reached tend,
        hit the step budget, or quarantined (evicted members count as
        terminally done so the batch — and the job — can drain)."""
        reached = g.t_host >= g.tend * (1.0 - _TEND_EPS) - 1e-300
        return reached | (g.nstep >= nstepmax) | g.quarantined

    def run_complete(self, params=None, tend=None) -> bool:
        """Every member individually reached its tend or the step
        budget (the supervisor's completion hook)."""
        nmax = int(self.params.run.nstepmax)
        return all(bool(self._group_done(g, nmax).all())
                   for g in self.groups)

    # ------------------------------------------------------------------
    def _dispatch(self, g: SubBatch, nsteps: int, eff_tend,
                  dt_scale: float = 1.0, summarize: bool = False,
                  fetch: bool = True):
        """One fused window for one sub-batch.

        With ``fetch`` (the default) returns ``(ndone[B], summ)`` with
        ``summ`` the per-member guard summary ``[B, 3]`` (None unless
        ``summarize``).  Exactly ONE host<->device fetch per call —
        ``jax.device_get`` on the ``(ndone, t[, summary])`` tuple — so
        arming the batched guard widens the existing fetch instead of
        adding one, and the zero-overhead pin can count
        ``jax.device_get`` calls honestly.  ``g.t_host`` is refreshed
        from the same fetch.

        With ``fetch=False`` the window is dispatched asynchronously
        and the un-fetched device refs ``(ndone, t[, summary])`` are
        returned instead: the chunk driver stacks every group's refs
        into a SINGLE ``jax.device_get`` (one host round-trip per
        chunk regardless of group count) and folds each tuple back via
        :meth:`_apply_fetch`."""
        tdt = g.t.dtype
        tend = jnp.asarray(eff_tend, tdt)
        summ_ref = None
        if self._slab_mesh is not None:
            t, ndone = self._dispatch_slab(g, nsteps, eff_tend)
        elif self.spec.solver == "hydro" and g.tables is not None:
            from ramses_tpu.grid.uniform import run_steps_cool_batch
            out = run_steps_cool_batch(
                g.grid, g.state[0], g.t, tend, nsteps, g.tables,
                g.cspec, dt_scale=dt_scale, summarize=summarize)
            u, t, ndone = out[:3]
            g.state = (u,)
            summ_ref = out[-1] if summarize else None
        elif self.spec.solver == "hydro":
            from ramses_tpu.grid.uniform import run_steps_batch
            out = run_steps_batch(
                g.grid, g.state[0], g.t, tend, nsteps,
                dt_scale=dt_scale, summarize=summarize)
            u, t, ndone = out[:3]
            g.state = (u,)
            summ_ref = out[-1] if summarize else None
        elif self.spec.solver == "mhd":
            from ramses_tpu.mhd.uniform import run_steps_batch
            out = run_steps_batch(
                g.grid, g.state[0], g.state[1], g.t, tend, nsteps,
                dt_scale=dt_scale, summarize=summarize)
            u, bf, t, ndone = out[:4]
            g.state = (u, bf)
            summ_ref = out[-1] if summarize else None
        else:
            from ramses_tpu.rhd.uniform import run_steps_batch
            out = run_steps_batch(
                g.grid, g.state[0], g.t, tend, nsteps,
                dt_scale=dt_scale, summarize=summarize)
            u, t, ndone = out[:3]
            g.state = (u,)
            summ_ref = out[-1] if summarize else None
        g.t = t
        refs = ((ndone, t) if summ_ref is None
                else (ndone, t, summ_ref))
        if not fetch:
            return refs
        return self._apply_fetch(g, jax.device_get(refs))

    @staticmethod
    def _apply_fetch(g: SubBatch, vals):
        """Fold one fetched ``(ndone, t[, summary])`` tuple back into
        the group's host mirrors; returns ``(ndone[B], summ)``."""
        g.t_host = np.asarray(vals[1], np.float64)
        summ = (np.asarray(vals[2], np.float64) if len(vals) > 2
                else None)
        return np.asarray(vals[0], np.int64), summ

    def _dispatch_slab(self, g: SubBatch, nsteps: int, eff_tend):
        """Slab-mode window: stream each active member through the
        explicit slab pipeline (:func:`ramses_tpu.parallel.halo.
        run_steps_halo`) on the full assigned mesh, one member at a
        time.  Per-member arrays, mesh and window sizes are identical
        to a standalone sharded run — the bitwise parity pin.  Members
        whose effective tend cannot advance them (done, frozen at the
        step budget, quarantined) are skipped with state untouched
        rather than burning a mesh-wide no-op window."""
        from ramses_tpu.parallel.halo import run_steps_halo
        eff = np.asarray(eff_tend, np.float64)
        us, ts, nds = [], [], []
        for i in range(g.size):
            if eff[i] <= g.t_host[i]:
                us.append(g.state[0][i])
                ts.append(g.t[i])
                nds.append(jnp.zeros((), jnp.int32))
                continue
            u, t, nd = run_steps_halo(g.grid, self._slab_mesh,
                                      g.state[0][i], g.t[i],
                                      float(eff[i]), nsteps)
            us.append(u)
            ts.append(t)
            nds.append(nd)
        g.state = (jnp.stack(us),)
        return jnp.stack(ts), jnp.stack(nds)

    def begin_chunk(self, chunk: Optional[int] = None,
                    nstepmax: Optional[int] = None) -> Dict[str, Any]:
        """Dispatch one fused window for every unfinished sub-batch
        WITHOUT blocking on the host fetch; returns the chunk context
        for :meth:`finish_chunk`.

        The begin/finish split exists for the gang driver
        (``ensemble/service.run_gang``): every co-scheduled job's
        windows are dispatched back-to-back — all submeshes compute
        concurrently — before any host thread blocks on results."""
        chunk = int(chunk or self.params.ensemble.chunk_steps or 16)
        nmax = int(nstepmax if nstepmax is not None
                   else self.params.run.nstepmax)
        guard = self._bguard
        if self._fault is not None:
            # top of chunk: the previous chunk's on_chunk beat has
            # already checkpointed, so a sigterm@K resume restarts
            # at nstep >= K and strict arming prevents a re-fire
            self._fault.maybe_signal(self.nstep)
            # zombie@K: stall the host thread past stale_timeout,
            # then resume — the queue's fencing token must refuse
            # this worker's writes from here on
            self._fault.maybe_zombie(self.nstep)
        t0 = time.perf_counter()
        pending: List[Tuple[SubBatch, np.ndarray, Any, Any]] = []
        for g in self.groups:
            done = self._group_done(g, nmax)
            if done.all():
                continue
            # members at tend idle via the in-scan mask; members at
            # the step budget (or quarantined) are frozen by
            # clamping their effective tend below their current t
            rem = nmax - int(g.nstep[~done].max()) if (~done).any() \
                else 0
            n = max(1, min(chunk, rem))
            if self._fault is not None:
                n = self._fault.clamp_window_batch(
                    n, self.nstep,
                    lambda j, _g=g: int(_g.nstep[_g.members.index(j)])
                    if j in _g.members else self.nstep)
            eff_tend = np.where((g.nstep >= nmax) | g.quarantined,
                                -1.0, g.tend)
            # the guard's retained pre-window state: plain refs
            # (run_steps_batch does not donate its inputs)
            prev = ((g.state, g.t, g.nstep.copy(),
                     g.t_host.copy()) if guard is not None else None)
            if self._fault is not None:
                self._fault.maybe_nan_batch(g)
            with (self._wd.guard("step") if self._wd is not None
                    else nullcontext()):
                if self._fault is not None:
                    self._fault.maybe_hang_batch(g, self.nstep)
                refs = self._dispatch(g, n, eff_tend,
                                      summarize=guard is not None,
                                      fetch=False)
            pending.append((g, done, prev, refs))
        return {"pending": pending, "t0": t0}

    def finish_chunk(self, ctx: Dict[str, Any]) -> int:
        """Fetch and fold back one chunk's results.

        A SINGLE stacked ``jax.device_get`` over every pending group's
        ``(ndone, t[, summary])`` refs — one host round-trip per chunk
        regardless of group count (pinned by the zero-overhead
        device_get counter tests) — then guard screening/recovery and
        step accounting per group.  Returns the steps advanced."""
        guard = self._bguard
        stepped = 0
        pending = ctx["pending"]
        fetched = []
        if pending:
            with (self._wd.guard("step") if self._wd is not None
                    else nullcontext()):
                fetched = jax.device_get([p[3] for p in pending])
        for (g, done, prev, _refs), vals in zip(pending, fetched):
            ndone, summ = self._apply_fetch(g, vals)
            if self._wd is not None:
                self._wd.note(nstep=self.nstep, t=self.t)
            if guard is not None:
                bad = guard.screen(g.t_host, summ, active=~done)
                if bad.any():
                    ndone = self._recover(g, bad, prev, ndone)
                    self._dirty = True
            g.nstep = g.nstep + ndone
            stepped += int(ndone.sum())
            self.cell_updates += int(ndone.sum()) * g.grid.ncell
        if stepped > 0 or self._fault is not None:
            self._dirty = True
        self.wall_s += time.perf_counter() - ctx["t0"]
        return stepped

    def run(self, chunk: Optional[int] = None,
            nstepmax: Optional[int] = None, verbose: bool = False,
            on_chunk: Optional[Callable[["EnsembleEngine"], None]] = None):
        """Drive every sub-batch until all members complete.

        ONE stacked host round-trip per chunk (``finish_chunk``),
        however many sub-batch groups the sweep split into;
        ``on_chunk`` (service heartbeats) runs after each chunk."""
        chunk = int(chunk or self.params.ensemble.chunk_steps or 16)
        nmax = int(nstepmax if nstepmax is not None
                   else self.params.run.nstepmax)
        while not self.run_complete():
            ctx = self.begin_chunk(chunk, nmax)
            stepped = self.finish_chunk(ctx)
            self.telemetry.record_event(
                "ensemble_chunk", nmember=self.nmember,
                ngroup=len(self.groups), steps=stepped,
                t_min=self.t, nstep_max=self.nstep,
                quarantined=self.quarantined_count,
                wall_s=round(self.wall_s, 6))
            if verbose:
                print(f"ensemble: {self.nmember} members "
                      f"{len(self.groups)} groups t_min={self.t:.5e} "
                      f"steps+={stepped} "
                      f"quarantined={self.quarantined_count}")
            if on_chunk is not None:
                on_chunk(self)
            if stepped == 0:
                # every active member was clamped to a no-op window —
                # cannot happen unless tend/nstepmax are inconsistent;
                # bail rather than spin
                break
        return self

    # ------------------------------------------------------------------
    # member isolation ladder: trip -> masked rollback -> halved-dt
    # retry -> LLF escalation regroup -> quarantine
    def _restore_members(self, g: SubBatch, mask: np.ndarray, prev):
        """Masked select of the retained pre-window state into the
        tripped lanes only — healthy members keep their advanced
        arrays bitwise untouched."""
        prev_state, prev_t, _prev_nstep, prev_t_host = prev
        m = jnp.asarray(mask)
        g.state = tuple(
            jnp.where(m.reshape((-1,) + (1,) * (cur.ndim - 1)), ps, cur)
            for ps, cur in zip(prev_state, g.state))
        g.t = jnp.where(m, prev_t, g.t)
        g.t_host = np.where(mask, prev_t_host, g.t_host)

    def _retry_masked(self, g: SubBatch, still: np.ndarray,
                      dt_scale: float):
        """Re-advance only the tripped lanes one step at reduced dt;
        everyone else idles via the effective-tend clamp (their state
        passes through the in-scan select bitwise unchanged)."""
        eff = np.where(still, g.tend, -1.0)
        ndone, summ = self._dispatch(g, 1, eff, dt_scale=dt_scale,
                                     summarize=True)
        ok = ~self._bguard.screen(g.t_host, summ)
        return ndone, ok

    def _retry_escalated(self, g: SubBatch, still: np.ndarray,
                         dt_scale: float):
        """LLF escalation as a *regroup*: the Riemann knob is a field
        of the frozen static config (a jit cache key), so the tripped
        members are gathered into an escalation sub-batch whose grid
        carries ``riemann='llf'``, advanced one step, and scattered
        back — never a traced branch."""
        import dataclasses as _dc
        idx = np.nonzero(still)[0]
        jidx = jnp.asarray(idx)
        esc = SubBatch(
            grid=_dc.replace(g.grid, cfg=_dc.replace(g.grid.cfg,
                                                     riemann="llf")),
            cspec=g.cspec,
            members=[g.members[i] for i in idx],
            state=tuple(c[jidx] for c in g.state),
            tables=(jax.tree_util.tree_map(lambda x: x[jidx], g.tables)
                    if g.tables is not None else None),
            t=g.t[jidx], tend=g.tend[idx],
            nstep=g.nstep[idx].copy(), t_host=g.t_host[idx].copy(),
            quarantined=np.zeros(len(idx), bool))
        nd_sub, summ = self._dispatch(esc, 1, esc.tend,
                                      dt_scale=dt_scale, summarize=True)
        ok_sub = ~self._bguard.screen(esc.t_host, summ)
        g.state = tuple(c.at[jidx].set(sc)
                        for c, sc in zip(g.state, esc.state))
        g.t = g.t.at[jidx].set(esc.t)
        g.t_host[idx] = esc.t_host
        ndone = np.zeros(g.size, np.int64)
        ndone[idx] = nd_sub
        ok = np.ones(g.size, bool)
        ok[idx] = ok_sub
        return ndone, ok

    def _recover(self, g: SubBatch, bad: np.ndarray, prev,
                 ndone: np.ndarray) -> np.ndarray:
        """Run the member isolation ladder for the tripped lanes of
        one window; returns the corrected per-member ndone (tripped
        lanes contribute only their recovered retry steps)."""
        sg = self._bguard
        _ps, _pt, prev_nstep, prev_t_host = prev
        ndone = np.array(ndone, np.int64)
        ndone[bad] = 0
        sg.record_trip([g.members[i] for i in np.nonzero(bad)[0]],
                       prev_nstep[bad], prev_t_host[bad])
        self._restore_members(g, bad, prev)
        still = bad.copy()
        riemann = getattr(g.grid.cfg, "riemann", None)
        can_llf = riemann is not None and riemann != "llf"
        for attempt in range(1, sg.max_retries + 1):
            scale = 0.5 ** attempt
            escalated = attempt >= 2 and can_llf
            sg.record_rollback(
                [g.members[i] for i in np.nonzero(still)[0]],
                attempt, scale, escalated)
            if escalated:
                nd_r, ok = self._retry_escalated(g, still, scale)
            else:
                nd_r, ok = self._retry_masked(g, still, scale)
            rec = still & ok
            if rec.any():
                ndone[rec] += nd_r[rec]
                sg.record_recovered(
                    [g.members[i] for i in np.nonzero(rec)[0]], attempt)
            still &= ~ok
            if not still.any():
                return ndone
            self._restore_members(g, still, prev)
        for i in np.nonzero(still)[0]:
            self._quarantine_member(g, int(i), int(prev_nstep[i]),
                                    float(prev_t_host[i]))
        return ndone

    def _quarantine_member(self, g: SubBatch, i: int, nstep0: int,
                           t0: float):
        """Evict lane ``i`` of group ``g``: emergency-dump its last
        clean state (already restored by the ladder), record the
        census entry, and freeze the lane so the batch drains without
        it.  The census rides every subsequent checkpoint manifest."""
        k = int(g.members[i])
        dump = ""
        try:
            dump = self._dump_member(g, i, k, nstep0, t0)
        except Exception as e:  # noqa: BLE001 — dump is best-effort
            print(f" batch guard: member {k} emergency dump failed: "
                  f"{e!r}")
        info = {"reason": "nonfinite_state", "nstep": nstep0,
                "t": t0, "dump": dump}
        self.quarantined[k] = info
        g.quarantined[i] = True
        self._bguard.record_quarantine(k, info)

    def _dump_member(self, g: SubBatch, i: int, k: int, nstep0: int,
                     t0: float) -> str:
        """Manifest-valid single-member emergency dump
        (``quarantine_mNNN/`` beside the ensemble checkpoints; the
        ``output_`` prefix is avoided so auto-resume never selects
        it)."""
        from ramses_tpu.resilience.checkpoint import finalize_checkpoint
        base = str(self.params.output.output_dir or ".")
        os.makedirs(base, exist_ok=True)
        final = os.path.join(base, f"quarantine_m{k:03d}")
        stage = final + ".tmp"
        os.makedirs(stage, exist_ok=True)
        arrays = {f"s{ci}": np.asarray(comp[i])
                  for ci, comp in enumerate(g.state)}
        np.savez(os.path.join(stage, "member_state.npz"),
                 t=np.float64(t0), nstep=np.int64(nstep0), **arrays)
        return finalize_checkpoint(
            stage, final, meta={"kind": "quarantine_member",
                                "member": k,
                                "reason": "nonfinite_state",
                                "nstep": nstep0, "t": t0,
                                **self.trace_meta})

    # ------------------------------------------------------------------
    # manifest-valid checkpoints (resilience/checkpoint) so a supervised
    # ensemble job resumes exactly like a solo run
    def save(self, base_dir: str, iout: Optional[int] = None) -> str:
        from ramses_tpu.resilience.checkpoint import finalize_checkpoint
        if (iout is None and not self._dirty and self._last_snap
                and os.path.dirname(self._last_snap)
                == os.path.abspath(base_dir)
                and os.path.isdir(self._last_snap)):
            # nothing stepped since the last snapshot: the checkpoint
            # on disk is bit-identical to what a rewrite would produce
            return self._last_snap
        self._iout = int(iout if iout is not None else self._iout + 1)
        final = os.path.join(base_dir, f"output_{self._iout:05d}")
        stage = final + ".tmp"
        os.makedirs(stage, exist_ok=True)
        try:
            if self._fault is not None:
                # enospc@K: the staging write raises OSError(ENOSPC)
                # — diskguard absorbs it one layer up
                self._fault.maybe_enospc(self.nstep)
            arrays: Dict[str, np.ndarray] = {}
            for gi, g in enumerate(self.groups):
                for ci, comp in enumerate(g.state):
                    arrays[f"g{gi}_s{ci}"] = np.asarray(comp)
                arrays[f"g{gi}_t"] = np.asarray(g.t)
                arrays[f"g{gi}_nstep"] = g.nstep
            np.savez(os.path.join(stage, "ensemble_state.npz"),
                     **arrays)
            census = {str(k): v
                      for k, v in sorted(self.quarantined.items())}
            with open(os.path.join(stage, "ensemble.json"), "w") as f:
                json.dump({"fingerprint": self.spec.fingerprint(),
                           "nmember": self.nmember,
                           "solver": self.spec.solver,
                           "groups": [g.members for g in self.groups],
                           "quarantined": census,
                           # informational: the packing the checkpoint
                           # was written under.  State arrays are saved
                           # host-global, so restore is elastic across
                           # packings — from_checkpoint re-places under
                           # whatever plan the restoring worker passes.
                           "packing": self.plan.describe(),
                           "iout": self._iout}, f, indent=1)
            meta = {"kind": "ensemble", "iout": self._iout,
                    "nstep": self.nstep, "t": self.t,
                    "nmember": self.nmember, **self.trace_meta}
            if census:
                # per-member quarantine census in the manifest meta:
                # the durable record (read_quarantine_census) of which
                # members were evicted, with reason/nstep/t
                meta["quarantined"] = census
            snap = finalize_checkpoint(stage, final, meta)
        except OSError:
            # a failed staging write (ENOSPC, dying disk) must not
            # leave a half-staged output_NNNNN.tmp behind — remove it
            # and retract the iout bump so the next save reuses it
            import shutil
            shutil.rmtree(stage, ignore_errors=True)
            self._iout -= 1
            raise
        self._dirty = False
        self._last_snap = os.path.abspath(snap)
        return snap

    @classmethod
    def from_checkpoint(cls, spec: EnsembleSpec, outdir: str,
                        dtype=jnp.float64, telemetry=None, plan=None
                        ) -> "EnsembleEngine":
        """Rebuild from an ensemble checkpoint dir (manifest-validated
        by the caller/supervisor); the spec must expand to the same
        members the checkpoint was written from.  ``plan`` names the
        packing for the *restored* run — it need not match the one the
        checkpoint was written under (cross-packing restore: the state
        arrays are host-global, and the loaded groups are simply
        re-placed under the new plan)."""
        with open(os.path.join(outdir, "ensemble.json")) as f:
            meta = json.load(f)
        eng = cls(spec, dtype=dtype, telemetry=telemetry, plan=plan)
        if meta["fingerprint"] != spec.fingerprint():
            raise ValueError(
                f"checkpoint {outdir} was written by a different "
                f"ensemble spec (fingerprint {meta['fingerprint']} != "
                f"{spec.fingerprint()})")
        if meta["groups"] != [g.members for g in eng.groups]:
            raise ValueError(f"checkpoint {outdir}: sub-batch grouping "
                             "changed; cannot restore")
        data = np.load(os.path.join(outdir, "ensemble_state.npz"))
        for gi, g in enumerate(eng.groups):
            g.state = tuple(jnp.asarray(data[f"g{gi}_s{ci}"], dtype)
                            for ci in range(len(g.state)))
            # cast to the engine's time dtype (g.t was initialised to
            # it): a checkpoint written under a different x64 mode must
            # not leak its dtype into the scan carry
            g.t = jnp.asarray(data[f"g{gi}_t"], g.t.dtype)
            g.t_host = np.asarray(data[f"g{gi}_t"], np.float64)
            g.nstep = np.asarray(data[f"g{gi}_nstep"], np.int64)
            # re-place the loaded arrays under THIS engine's plan (the
            # checkpoint's own packing is irrelevant — elastic restore)
            eng._place_group(g)
        eng.quarantined = {int(k): dict(v) for k, v in
                           (meta.get("quarantined") or {}).items()}
        for k in eng.quarantined:
            g, i = eng._member_pos(k)
            g.quarantined[i] = True
        eng._iout = int(meta.get("iout", 0))
        # the restored-from snapshot is current until a step lands
        eng._dirty = False
        eng._last_snap = os.path.abspath(outdir)
        return eng
