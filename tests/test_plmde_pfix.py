"""PLMDE scheme (``hydro/uplmde.f90``), dual-energy pressure fix
(``hydro/godunov_fine.f90`` divu/enew + add_pdv + set_uold), and ISM
cooling (``hydro/cooling_module_ism.f90``)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.driver import Simulation


def _sod_groups(scheme="muscl", lmin=7, **hydro_extra):
    h = {"gamma": 1.4, "courant_factor": 0.5, "riemann": "hllc",
         "slope_type": 1, "scheme": scheme}
    h.update(hydro_extra)
    return {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmin, "boxlen": 1.0},
        "boundary_params": {"nboundary": 2,
                            "ibound_min": [-1, 1], "ibound_max": [-1, 1],
                            "bound_type": [2, 2]},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75], "length_x": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.125],
                        "p_region": [1.0, 0.1]},
        "hydro_params": h,
        "output_params": {"noutput": 1, "tout": [0.2], "tend": 0.2},
    }


def test_plmde_sod_matches_muscl_accuracy():
    """PLMDE solves Sod with accuracy comparable to MUSCL-Hancock."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from exact_riemann import exact_riemann

    sols = {}
    for scheme in ("muscl", "plmde"):
        sim = Simulation(params_from_dict(_sod_groups(scheme), ndim=1),
                         dtype=jnp.float64)
        sim.evolve()
        sols[scheme] = np.asarray(sim.state.u)[0]
    n = len(sols["muscl"])
    x = (np.arange(n) + 0.5) / n
    rho_ex = exact_riemann(1.0, 0.0, 1.0, 0.125, 0.0, 0.1, 1.4,
                           x, 0.2)[0]
    l1 = {s: np.abs(v - rho_ex).mean() for s, v in sols.items()}
    assert l1["plmde"] < 1.5 * l1["muscl"], l1
    assert l1["plmde"] < 0.01


def test_plmde_conservation_2d():
    g = _sod_groups("plmde", lmin=5)
    g["boundary_params"] = {}
    g["init_params"]["y_center"] = [0.5, 0.5]
    g["init_params"]["length_y"] = [10.0, 0.3]
    g["init_params"]["exp_region"] = [10.0, 2.0]
    g["output_params"] = {"tend": 0.05}
    sim = Simulation(params_from_dict(g, ndim=2), dtype=jnp.float64)
    u0 = np.asarray(sim.state.u).copy()
    sim.evolve()
    u1 = np.asarray(sim.state.u)
    assert sim.state.nstep > 3
    for iv in range(u0.shape[0]):
        assert np.isclose(u1[iv].sum(), u0[iv].sum(), rtol=1e-11,
                          atol=1e-12)


def test_pressure_fix_cold_supersonic_flow():
    """Cold hypersonic advection in float32: eint/ekin ~ 5e-8 sits
    below single-precision epsilon, so E-ekin is pure truncation noise
    — the regime the dual-energy fix exists for.  With pressure_fix +
    beta_fix the recovered pressure stays positive and near its
    initial value; the unfixed run's is garbage (or negative)."""
    def run(pfix):
        g = {
            "run_params": {"hydro": True},
            "amr_params": {"levelmin": 6, "levelmax": 6, "boxlen": 1.0},
            "init_params": {"nregion": 2,
                            "region_type": ["square", "square"],
                            "x_center": [0.5, 0.5],
                            "length_x": [10.0, 0.25],
                            "exp_region": [10.0, 2.0],
                            "d_region": [1.0, 10.0],
                            "p_region": [1e-6, 1e-6],
                            "u_region": [10.0, 10.0]},
            "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                             "riemann": "hllc",
                             "pressure_fix": pfix, "beta_fix": 0.5},
            "output_params": {"tend": 0.02},
        }
        sim = Simulation(params_from_dict(g, ndim=1), dtype=jnp.float32)
        sim.evolve()
        u = np.asarray(sim.state.u, dtype=np.float64)
        rho = u[0]
        p = 0.4 * (u[2] - 0.5 * u[1] ** 2 / rho)
        return rho, p

    rho_f, p_f = run(True)
    rho_n, p_n = run(False)
    # the fix guarantees positive recovered pressure where truncation
    # noise drives E - ekin negative; the unfixed run goes negative.
    # (Absolute f32 pressure accuracy at eint/ekin ~ 5e-8 is limited by
    # the per-step E - ekin rounding either way — the reference runs
    # this machinery in f64, where the enew replacement is exact.)
    assert p_f.min() > 0, p_f.min()
    assert p_n.min() < 0, p_n.min()
    # density profile essentially unaffected by the fix
    np.testing.assert_allclose(rho_f, rho_n, rtol=1e-4, atol=1e-5)


def test_pressure_fix_enew_accuracy_f64():
    """In f64 the separately-advected internal energy recovers the
    tiny pressure accurately through a strong compression where the
    total-energy route is still fine — the two must agree closely
    (consistency of the enew path with the conservative one)."""
    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 7, "levelmax": 7, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75],
                        "length_x": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 1.0],
                        "p_region": [1.0, 0.1],
                        "u_region": [0.5, -0.5]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                         "riemann": "hllc",
                         "pressure_fix": True, "beta_fix": 0.5},
        "output_params": {"tend": 0.1},
    }
    sim_f = Simulation(params_from_dict(
        {k: dict(v) for k, v in g.items()}, ndim=1), dtype=jnp.float64)
    sim_f.evolve()
    g["hydro_params"]["pressure_fix"] = False
    sim_n = Simulation(params_from_dict(g, ndim=1), dtype=jnp.float64)
    sim_n.evolve()
    uf = np.asarray(sim_f.state.u)
    un = np.asarray(sim_n.state.u)
    pf = 0.4 * (uf[2] - 0.5 * uf[1] ** 2 / uf[0])
    pn = 0.4 * (un[2] - 0.5 * un[1] ** 2 / un[0])
    # subsonic colliding flows: fix must not alter a well-resolved run
    np.testing.assert_allclose(pf, pn, rtol=1e-6)


@pytest.mark.slow
def test_pressure_fix_on_amr_blast():
    """The fix rides the AMR stencil + dense sweeps without breaking
    mass conservation."""
    from ramses_tpu.amr.hierarchy import AmrSim
    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 6, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [10.0, 0.25], "length_y": [10.0, 0.25],
                        "length_z": [10.0, 0.25],
                        "exp_region": [10.0, 2.0],
                        "d_region": [1.0, 1.0],
                        "p_region": [1e-5, 10.0]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                         "pressure_fix": True, "beta_fix": 0.5},
        "refine_params": {"err_grad_p": 0.2},
        "output_params": {"tend": 0.02},
    }
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    m0 = sim.totals()[0]
    sim.evolve(0.02, nstepmax=8)
    m1 = sim.totals()[0]
    assert np.isclose(m1, m0, rtol=1e-11)      # fix touches E only
    assert sim.tree.noct(5) > 0
    for l in sim.levels():
        assert np.isfinite(np.asarray(sim.u[l])).all()


def test_ism_cooling_two_phase_equilibrium():
    """The Audit & Hennebelle net rate supports the classic two-phase
    ISM: warm (~7000 K) equilibrium at n=0.5, cold (~40 K) at n=100."""
    from ramses_tpu.hydro.cooling import _ism_rate, solve_cooling_ism
    for n, lo, hi in ((0.5, 4000.0, 12000.0), (100.0, 10.0, 120.0)):
        Ts = np.logspace(1, 4.3, 200)
        r = np.asarray(_ism_rate(jnp.asarray(Ts), jnp.full(200, n)))
        sc = np.where(np.diff(np.sign(r)))[0]
        assert len(sc) >= 1
        Teq = Ts[sc[0]]
        assert lo < Teq < hi, (n, Teq)
    # integrator relaxes toward equilibrium from both sides
    out = np.asarray(solve_cooling_ism(
        jnp.asarray([100.0, 100.0]), jnp.asarray([1e5, 3.0]), 3.15e13))
    assert out[0] < 1e4        # hot gas cooled hard at n=100
    assert out[1] > 3.0        # cold gas heated


def test_ism_cooling_through_driver():
    """cooling_ism=.true. routes cooling_step to the ISM module."""
    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "z_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "length_z": [10.0], "exp_region": [10.0],
                        "d_region": [100.0], "p_region": [100.0]},
        "hydro_params": {"gamma": 1.6666667, "courant_factor": 0.5},
        "cooling_params": {"cooling": True, "cooling_ism": True},
        "units_params": {"units_density": 1.66e-24,
                         "units_time": 3.15e13,
                         "units_length": 3.08e18},
        "output_params": {"tend": 0.05},
    }
    sim = Simulation(params_from_dict(g, ndim=3), dtype=jnp.float64)
    assert sim.cool_spec.ism
    e0 = float(np.asarray(sim.state.u)[4].sum())
    sim.evolve()
    e1 = float(np.asarray(sim.state.u)[4].sum())
    assert e1 < e0 * (1 - 1e-6)       # dense hot box radiates
