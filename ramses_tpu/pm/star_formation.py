"""Star formation (Schmidt law) and supernova feedback.

Reference: ``pm/star_formation.f90`` (threshold + Poisson sampling,
``:536-574``) and ``pm/feedback.f90`` (``thermal_feedback:6``, SN specific
energy 1e51 erg / 10 Msun, ``:231``).

These passes run at coarse-step cadence on the host (numpy): particle
creation is a data-dependent append, the one operation that fights XLA's
static shapes — exactly the part the reference also treats as scalar
bookkeeping between vectorized sweeps.  Gas state transfers back as a
device array; everything else stays fused on device.
"""

from __future__ import annotations

from dataclasses import dataclass, replace as dreplace

import jax.numpy as jnp
import numpy as np

from ramses_tpu.pm.particles import FAM_STAR, ParticleSet
from ramses_tpu.units import Units, factG_in_cgs, yr2sec

M_SUN = 1.9891e33
FLAG_SN_DONE = 1


@dataclass(frozen=True)
class SfSpec:
    """&SF_PARAMS + &FEEDBACK_PARAMS subset (amr/amr_parameters.f90:141-164)."""
    enabled: bool = False
    n_star: float = 0.1          # SF density threshold [H/cc]
    t_star: float = 0.0          # SF timescale at threshold [Gyr]
    eps_star: float = 0.0        # efficiency per free-fall when t_star=0
    m_star: float = -1.0         # particle mass in units of the quantum
    T2_star: float = 0.0         # ISM polytrope normalization [K]
    g_star: float = 1.0          # ISM polytrope index
    # feedback
    eta_sn: float = 0.0          # ejecta mass fraction
    yield_metal: float = 0.1
    t_sne: float = 10.0          # delay [Myr]
    f_w: float = 0.0             # wind mass loading; >0 => kinetic mode

    @classmethod
    def from_params(cls, p) -> "SfSpec":
        raw_sf = p.raw.get("sf_params", {}) if p.raw else {}
        raw_fb = p.raw.get("feedback_params", {}) if p.raw else {}

        def g(d, k, dflt):
            v = d.get(k, dflt)
            return v[0] if isinstance(v, list) else v

        return cls(
            enabled=bool(raw_sf),
            n_star=float(g(raw_sf, "n_star", 0.1)),
            t_star=float(g(raw_sf, "t_star", 0.0)),
            eps_star=float(g(raw_sf, "eps_star", 0.0)),
            m_star=float(g(raw_sf, "m_star", -1.0)),
            T2_star=float(g(raw_sf, "t2_star", 0.0)),
            g_star=float(g(raw_sf, "g_star", 1.0)),
            eta_sn=float(g(raw_fb, "eta_sn", 0.0)),
            yield_metal=float(g(raw_fb, "yield", 0.1)),
            t_sne=float(g(raw_fb, "t_sne", 10.0)),
            f_w=float(g(raw_fb, "f_w", 0.0)))


def mstar_quantum(spec: SfSpec, units: Units, dx_min: float,
                  ndim: int) -> float:
    """Star particle mass [code]: n_star·vol_min by default, or
    m_star·mass_sph (``star_formation.f90:154-158``)."""
    vol_min = dx_min ** ndim
    base = spec.n_star / units.scale_nH * vol_min
    return base if spec.m_star <= 0 else spec.m_star * base


def sf_timescale_code(rho, nH, spec: SfSpec, units: Units):
    """SF timescale in code units: t_star·(nH/n_star)^-1/2, or
    t_ff/eps_star (``star_formation.f90:536-560``) — shared by the
    uniform and AMR passes."""
    if spec.t_star > 0:
        tstar_s = (spec.t_star * 1e9 * yr2sec
                   * np.sqrt(spec.n_star / np.maximum(nH, 1e-30)))
    else:
        rho_cgs = rho * units.scale_d
        t_ff = np.sqrt(3 * np.pi / (32 * factG_in_cgs
                                    * np.maximum(rho_cgs, 1e-300)))
        tstar_s = t_ff / max(spec.eps_star, 1e-10)
    return tstar_s / units.scale_t


def append_stars(p: ParticleSet, xnew: np.ndarray, vnew: np.ndarray,
                 counts: np.ndarray, mstar: float, t: float,
                 next_id: int):
    """Append ``counts[i]`` FAM_STAR particles at ``xnew[i]``/``vnew[i]``
    into free slots of ``p`` (truncating at capacity, keeping the
    earliest cells — the reference's ``nstar_tot`` overflow policy).

    Returns (p', next_id', kept_counts) where ``kept_counts`` mirrors
    ``counts`` after truncation so callers remove exactly the gas that
    became stars.  Shared by the uniform and AMR SF passes.
    """
    active = np.asarray(p.active)
    free = np.where(~active)[0]
    ntot = int(counts.sum())
    kept = counts.copy()
    if len(free) < ntot:
        keep = np.cumsum(counts) <= len(free)
        kept = np.where(keep, counts, 0)
        ntot = int(kept.sum())
    if ntot == 0:
        return p, next_id, kept
    slots = free[:ntot]
    sel = kept > 0
    rep = np.repeat(np.arange(len(counts))[sel], kept[sel])

    x_arr = np.array(p.x)
    v_arr = np.array(p.v)
    m_arr = np.array(p.m)
    act = active.copy()
    fam = np.array(p.family)
    tp = np.array(p.tp)
    idp = np.array(p.idp)
    flg = np.array(p.flags)
    x_arr[slots] = xnew[rep]
    v_arr[slots] = vnew[rep]
    m_arr[slots] = mstar
    act[slots] = True
    fam[slots] = FAM_STAR
    tp[slots] = t
    idp[slots] = next_id + np.arange(ntot)
    flg[slots] = 0
    p2 = dreplace(p, x=jnp.asarray(x_arr), v=jnp.asarray(v_arr),
                  m=jnp.asarray(m_arr), active=jnp.asarray(act),
                  family=jnp.asarray(fam), tp=jnp.asarray(tp),
                  idp=jnp.asarray(idp), flags=jnp.asarray(flg))
    return p2, next_id + ntot, kept


def star_formation(u, p: ParticleSet, rng: np.random.Generator,
                   spec: SfSpec, units: Units, dx: float, t: float,
                   dt: float, next_id: int):
    """One SF pass over a dense state ``u [nvar, *sp]`` (host numpy).

    Returns (u', particles', next_id').  Poisson-samples
    N ~ P(mgas/mstar · dt/t_star(ρ)) per eligible cell
    (``star_formation.f90:561-574``), caps at 90% of the cell gas, removes
    the mass at the cell's velocity, appends FAM_STAR particles.
    """
    u = np.array(u)
    ndim = u.ndim - 1
    vol = dx ** ndim
    rho = u[0]
    nH = rho * units.scale_nH
    eligible = nH > spec.n_star
    if not eligible.any():
        return u, p, next_id

    mstar = mstar_quantum(spec, units, dx, ndim)
    tstar_code = sf_timescale_code(rho, nH, spec, units)

    lam = np.where(eligible, rho * vol / mstar * dt / tstar_code, 0.0)
    nnew = rng.poisson(lam)
    # cap: at most 90% of the cell's gas (``:569``)
    cap = (0.9 * rho * vol / mstar).astype(np.int64)
    nnew = np.minimum(nnew, np.maximum(cap, 0))
    idx = np.argwhere(nnew > 0)
    if len(idx) == 0:
        return u, p, next_id

    counts = nnew[tuple(idx.T)]
    cells = tuple(idx.T)
    xnew = (idx + 0.5) * dx
    vel = np.stack([u[1 + d][cells] / np.maximum(u[0][cells], 1e-300)
                    for d in range(ndim)], axis=1)
    p2, next_id, kept = append_stars(p, xnew, vel, counts, mstar, t,
                                     next_id)
    if kept.sum() == 0:
        return u, p, next_id

    # remove exactly the gas that became stars, at the cell velocity
    # (momentum/energy scale proportionally)
    dm = kept * mstar / vol                          # density removed
    frac = 1.0 - dm / rho[cells]
    for iv in range(u.shape[0]):
        u[iv][cells] = u[iv][cells] * frac
    return u, p2, next_id


def thermal_feedback(u, p: ParticleSet, spec: SfSpec, units: Units,
                     dx: float, t: float):
    """Delayed thermal SN dumps (``pm/feedback.f90:6-231,351``): stars
    older than t_sne return eta_sn of their mass and inject
    1e51 erg / 10 Msun of specific ejecta energy into their cell, once."""
    if spec.eta_sn <= 0:
        return u, p
    u = np.array(u)
    ndim = u.ndim - 1
    vol = dx ** ndim
    due = sn_due_mask(p, spec, units, t)
    if not due.any():
        return u, p

    # specific SN energy in code units (feedback.f90:231)
    esn_code = (1e51 / (10.0 * M_SUN)) / units.scale_v ** 2
    xdue = np.asarray(p.x)[due]
    mdue = np.asarray(p.m)[due]
    mej = spec.eta_sn * mdue
    cells = tuple(np.clip((xdue[:, d] / dx).astype(np.int64), 0,
                          u.shape[1 + d] - 1) for d in range(ndim))
    np.add.at(u[0], cells, mej / vol)
    vstar = np.asarray(p.v)[due]
    for d in range(ndim):
        np.add.at(u[1 + d], cells, mej * vstar[:, d] / vol)
    # kinetic energy of the returned mass + SN thermal energy
    ek = 0.5 * mej * (vstar ** 2).sum(axis=1)
    np.add.at(u[1 + ndim], cells, (ek + mej * esn_code) / vol)

    m_arr = np.array(p.m)
    m_arr[due] = m_arr[due] - mej
    flg = np.array(p.flags)
    flg[due] |= FLAG_SN_DONE
    p2 = dreplace(p, m=jnp.asarray(m_arr), flags=jnp.asarray(flg))
    return u, p2


def sn_due_mask(p: ParticleSet, spec: SfSpec, units: Units, t: float):
    """Active stars past the SN delay whose event hasn't fired."""
    age_code = t - np.asarray(p.tp)
    t_sne_code = spec.t_sne * 1e6 * yr2sec / units.scale_t
    return (np.asarray(p.active)
            & (np.asarray(p.family) == FAM_STAR)
            & (np.asarray(p.flags) & FLAG_SN_DONE == 0)
            & (age_code > t_sne_code))


def wind_shell(ndim: int):
    """(offsets [nc, ndim], rhat [nc, ndim]) of the 3^ndim SN bubble —
    the one-cell ``rbubble`` of the kinetic scheme; the central cell's
    unit vector is zero (its share of the wind energy goes thermal)."""
    offs = (np.indices((3,) * ndim).reshape(ndim, -1).T - 1)
    rr = np.sqrt((offs ** 2).sum(axis=1))
    rhat = np.where(rr[:, None] > 0, offs / np.maximum(rr[:, None], 1.0),
                    0.0)
    return offs, rhat


def kinetic_feedback(u, p: ParticleSet, spec: SfSpec, units: Units,
                     dx: float, t: float, bc=None):
    """Delayed KINETIC SN winds, the mass-loaded momentum scheme
    (Dubois & Teyssier; ``pm/feedback.f90`` f_w path): each event
    sweeps ``f_w`` x the ejecta mass from the host cell and launches
    ``(1+f_w)·m_ej`` through the 3^ndim bubble with the wind speed
    ``v_w = sqrt(2 E_SN / m_load)`` radially outward; the central
    share of the wind energy is deposited thermally."""
    if spec.eta_sn <= 0:
        return u, p
    u = np.array(u)
    ndim = u.ndim - 1
    vol = dx ** ndim
    due = sn_due_mask(p, spec, units, t)
    if not due.any():
        return u, p

    esn_code = (1e51 / (10.0 * M_SUN)) / units.scale_v ** 2
    xdue = np.asarray(p.x)[due]
    mej = spec.eta_sn * np.asarray(p.m)[due]
    vstar = np.asarray(p.v)[due]
    cells = np.stack([np.clip((xdue[:, d] / dx).astype(np.int64), 0,
                              u.shape[1 + d] - 1)
                      for d in range(ndim)], axis=1)      # [nsn, ndim]

    # sweep up f_w*mej from the host cell, capped at 25% of its gas
    # (the reference caps the swept fraction so rho stays positive).
    # SNe sharing a host cell must debit it ONCE for their combined
    # draw (fancy-index *= is last-write-wins): group per unique cell,
    # cap the TOTAL, hand each SN its proportional share.
    host = tuple(cells.T)
    lin = np.ravel_multi_index(host, u.shape[1:])
    uniq, inv = np.unique(lin, return_inverse=True)
    flat = u.reshape(u.shape[0], -1)
    mcell_u = flat[0][uniq] * vol
    tot_req = np.bincount(inv, weights=spec.f_w * mej)
    tot_allow = np.minimum(tot_req, 0.25 * mcell_u)
    msw = spec.f_w * mej * (tot_allow
                            / np.maximum(tot_req, 1e-300))[inv]
    mcell = mcell_u[inv]
    vcell = np.stack([flat[1 + d][uniq][inv]
                      / np.maximum(flat[0][uniq][inv], 1e-300)
                      for d in range(ndim)], axis=1)
    e_removed = (msw / np.maximum(mcell, 1e-300)
                 * flat[1 + ndim][uniq][inv] * vol)
    frac_u = 1.0 - tot_allow / np.maximum(mcell_u, 1e-300)
    flat[:, uniq] *= frac_u

    # launch the loaded shell: the bulk velocity carries the combined
    # momentum of ejecta + swept gas (momentum conservation exact by
    # construction), the radial wind kick carries the SN energy
    mload = mej + msw
    vw = np.sqrt(2.0 * esn_code * mej / np.maximum(mload, 1e-300))
    offs, rhat = wind_shell(ndim)
    nc = len(offs)
    vbulk = (mej[:, None] * vstar + msw[:, None] * vcell) \
        / np.maximum(mload[:, None], 1e-300)
    e_inj = np.zeros(len(mej))
    # bubble cells crossing a NON-periodic wall fold into the host cell
    # with the radial kick suppressed (their wind share goes thermal via
    # the budget line) — wrapping there would inject on the far side of
    # the box.  Faces are per-side: the periodic side of a mixed axis
    # still wraps (``BoundarySpec.from_params`` sets sides independently).
    def wall(d, side):
        return bc is not None and bc.faces[d][side].kind != 0

    for k in range(nc):
        raw = cells + offs[k]
        oob = np.zeros(len(mej), dtype=bool)
        for d in range(ndim):
            n = u.shape[1 + d]
            if wall(d, 0):
                oob |= raw[:, d] < 0
            if wall(d, 1):
                oob |= raw[:, d] >= n
        tgt = tuple(np.where(oob, cells[:, d],
                             raw[:, d] % u.shape[1 + d])
                    for d in range(ndim))
        central = np.logical_or(bool((offs[k] == 0).all()), oob)
        mshare = mload / nc
        vk = np.where(central[:, None], vbulk,
                      vbulk + vw[:, None] * rhat[k])
        np.add.at(u[0], tgt, mshare / vol)
        for d in range(ndim):
            np.add.at(u[1 + d], tgt, mshare * vk[:, d] / vol)
        ek = 0.5 * mshare * (vk ** 2).sum(axis=1)
        np.add.at(u[1 + ndim], tgt, ek / vol)
        e_inj += ek
    # exact energy budget: removed host energy + SN energy + ejecta
    # bulk KE, minus what the shell kicks already carry, lands as heat
    # in the host cell (the shock-heated mixing term)
    e_target = (e_removed + mej * esn_code
                + 0.5 * mej * (vstar ** 2).sum(axis=1))
    np.add.at(u[1 + ndim], host, (e_target - e_inj) / vol)

    m_arr = np.array(p.m)
    m_arr[due] = m_arr[due] - mej
    flg = np.array(p.flags)
    flg[due] |= FLAG_SN_DONE
    return u, dreplace(p, m=jnp.asarray(m_arr), flags=jnp.asarray(flg))
