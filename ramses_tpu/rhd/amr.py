"""Special-relativistic hydrodynamics on the AMR hierarchy.

The rhd solver family of the reference shadows the amr driver files with
relativistic kernels (``rhd/`` own umuscl/godunov_utils/condinit,
SURVEY.md §2.4); here the same inversion happens through the physics
dispatch in ``amr/kernels.py``: :class:`RhdAmrSim` IS :class:`AmrSim`
with the static cfg swapped to :class:`~ramses_tpu.rhd.core.RhdStatic`,
so prolongation/restriction/flux-correction/subcycling/regrid machinery
is shared and only the sweep kernels, the Courant evaluation, and the
refinement criteria (Lorentz-gradient) are relativistic.

Restrictions (the reference rhd solver has the same shape): no
self-gravity coupling, no particles, no cosmology — SRHD in c=1 units.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import Params
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.rhd import core
from ramses_tpu.rhd.core import RhdStatic
from ramses_tpu.rhd.driver import rhd_region_prims


class RhdAmrSim(AmrSim):
    """Adaptive SRHD run: region ICs, Lorentz/gradient refinement."""

    _tracer_physics = False    # (D, S) are not coordinate velocities

    @staticmethod
    def _make_cfg(params: Params):
        return RhdStatic.from_params(params)

    def __init__(self, params: Params, dtype=jnp.float64, **kw):
        if bool(params.run.poisson) or bool(params.run.pic):
            raise NotImplementedError(
                "rhd-amr: self-gravity/particles are not part of the "
                "SRHD solver family (reference rhd/ has no poisson "
                "coupling)")
        if bool(params.run.cosmo):
            raise NotImplementedError("rhd-amr: no cosmology (c=1 units)")
        spec = bmod.BoundarySpec.from_params(params)
        for lo, hi in ((f[0].kind, f[1].kind) for f in spec.faces):
            for k in (lo, hi):
                if k == bmod.INFLOW:
                    raise NotImplementedError(
                        "rhd boundaries: periodic/outflow/reflect only")
        super().__init__(params, dtype=dtype, **kw)

    def _ic_state(self, lvl: int) -> jnp.ndarray:
        """Relativistic conservative ICs on this level's padded cells."""
        m = self.maps[lvl]
        centers = self.tree.cell_centers(lvl, self.boxlen)
        x = [centers[:, d] for d in range(self.cfg.ndim)]
        q = rhd_region_prims(x, self.params, self.cfg)   # [nvar, ncell]
        u = np.asarray(core.prim_to_cons(jnp.asarray(q), self.cfg))
        # pad rows: floor-state vacuum (D=smallr at rest)
        qvac = np.zeros((self.cfg.nvar, 1))
        qvac[0] = self.cfg.smallr
        qvac[4] = self.cfg.smallp
        uvac = np.asarray(core.prim_to_cons(jnp.asarray(qvac), self.cfg))
        out = np.tile(uvac.T, (m.ncell_pad, 1))
        out[:u.shape[1]] = u.T
        return self._place(jnp.asarray(out, dtype=self.dtype), "cells")

    # ------------------------------------------------------------------
    # snapshot / restart: the generic writer with RELATIVISTIC
    # primitive conversion (the rhd solver family's own output_hydro
    # shadow writes rho, v, P — con→prim via the pressure Newton)
    # ------------------------------------------------------------------
    def _rhd_var_names(self):
        names = ["density", "velocity_x", "velocity_y", "velocity_z",
                 "pressure"]
        names += [f"scalar_{i:02d}" for i in range(self.cfg.npassive)]
        return names

    def dump(self, iout: int = 1, base_dir: str = ".",
             namelist_path=None, ncpu: int = 1) -> str:
        from ramses_tpu.io import snapshot as snapmod

        def to_out(rows):
            q = core.cons_to_prim(jnp.asarray(rows.T), self.cfg)
            return np.asarray(q, dtype=np.float64).T

        snap = snapmod.snapshot_from_amr(
            self, iout, to_out=to_out, names=self._rhd_var_names(),
            nvar_raw=self.cfg.nvar, gamma=self.cfg.gamma)
        return snapmod.dump_all(snap, iout, base_dir,
                                namelist_path=namelist_path, ncpu=ncpu)

    @classmethod
    def from_snapshot(cls, params: Params, outdir: str,
                      dtype=jnp.float64) -> "RhdAmrSim":
        from ramses_tpu.amr.hierarchy import (_place_u_rows,
                                              restore_amr_scaffold)
        cfg = RhdStatic.from_params(params)

        def to_cons(q):
            return np.asarray(core.prim_to_cons(jnp.asarray(q.T), cfg),
                              dtype=np.float64).T

        sim, _parts = restore_amr_scaffold(
            cls, params, outdir, dtype, to_cons=to_cons,
            place_level=_place_u_rows)
        return sim

    # ------------------------------------------------------------------
    # diagnostics
    # ------------------------------------------------------------------
    def leaf_prims(self, lvl: int):
        """(centers, primitives [n, nvar]) of leaf cells at one level."""
        xc, u = self.leaf_sample(lvl)
        q = np.asarray(core.cons_to_prim(jnp.asarray(u.T), self.cfg))
        return xc, q.T

    def max_lorentz(self) -> float:
        w = 1.0
        for l in self.levels():
            _, q = self.leaf_prims(l)
            if len(q):
                v2 = (q[:, 1:4] ** 2).sum(axis=1)
                w = max(w, float(
                    (1.0 / np.sqrt(np.maximum(1.0 - v2, 1e-14))).max()))
        return w
