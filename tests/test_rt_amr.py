"""RT on the AMR hierarchy (``rt/amr.py`` — the per-level subcycled
``rt_step`` of ``amr/amr_step.f90:594-672``; gray 1-group and the
multigroup 3-ion H/He/He+ ladder)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.amr.hierarchy import AmrSim

UNITS = {"units_density": 1.66e-24, "units_time": 3.15e13,
         "units_length": 3.08e18}


def _rt_groups(lmin, lmax, heating=False, refine=None, tend=0.01):
    g = {
        "run_params": {"hydro": True, "rt": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmax,
                       "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "z_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "length_z": [10.0],
                        "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1e-4]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "rt_params": {"rt_ndot": 1e48, "rt_c_fraction": 1e-4,
                      "rt_src_pos": [0.5, 0.5, 0.5], "rt_otsa": True,
                      "rt_heating": heating},
        "units_params": dict(UNITS),
        "output_params": {"tend": tend},
    }
    if refine:
        g["refine_params"] = refine
    return g


def test_rt_amr_matches_uniform_on_complete_level():
    """A levelmin==levelmax AMR run's ionized volume tracks the
    uniform RtCoupled path on the same grid."""
    from ramses_tpu.driver import Simulation

    tend = 0.004
    g = _rt_groups(4, 4, tend=tend)
    asim = AmrSim(params_from_dict({k: dict(v) for k, v in g.items()},
                                   ndim=3), dtype=jnp.float64)
    asim.evolve(tend, nstepmax=3)
    v_amr = asim.rt_amr.ionized_volume(asim)

    usim = Simulation(params_from_dict(
        {k: dict(v) for k, v in g.items()}, ndim=3), dtype=jnp.float64)
    usim.evolve()
    # compare through the RT sim's own measure (code volume)
    x_uni = np.asarray(usim.rt.sim.x)
    v_uni = float(x_uni.sum()) * usim.dx ** 3
    assert v_amr > 0.05 and v_uni > 0.05
    assert abs(v_amr - v_uni) < 0.35 * max(v_amr, v_uni), (v_amr, v_uni)


def test_rt_amr_refined_front_and_heating():
    """With a geometrically refined centre, the fine level ionizes
    around the source, photoheating raises the gas energy, and regrid
    migration keeps the radiation state consistent."""
    refine = {"r_refine": [0.15] * 8, "x_refine": [0.5] * 8,
              "y_refine": [0.5] * 8, "z_refine": [0.5] * 8}
    g = _rt_groups(4, 5, heating=True, refine=refine, tend=0.001)
    # denser gas + weaker source: the I-front stays INSIDE the refined
    # region so its radial profile is measurable on the fine level
    g["init_params"]["d_region"] = [10.0]
    g["rt_params"]["rt_ndot"] = 1e44
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    assert sim.tree.noct(5) > 0
    e0 = sim.totals()[4]
    v0 = sim.rt_amr.ionized_volume(sim)
    sim.evolve(0.001, nstepmax=2)
    v1 = sim.rt_amr.ionized_volume(sim)
    assert v1 > 1.5 * v0                      # front swept outward
    assert sim.totals()[4] > e0               # photoheated
    lmax = max(sim.levels())
    x = np.asarray(sim.rt_amr.xion[lmax])[:sim.maps[lmax].noct * 8]
    assert x.max() > 0.99                     # source cells ionized
    # the front is RADIALLY ordered on the refined level — this is the
    # row-order canary: oct/cell-major scrambles flatten the profile
    xc = sim.tree.cell_centers(lmax, sim.boxlen)
    rr = np.sqrt(((xc - 0.5) ** 2).sum(axis=1))
    near = x[:len(xc)][rr < 0.04].mean()
    far = x[:len(xc)][(rr > 0.11) & (rr < 0.145)].mean()
    assert near > 0.8 and far < 0.1, (near, far)
    # all levels hold sane radiation state after regrids
    for l in sim.levels():
        rad = np.asarray(sim.rt_amr.rad[l])
        assert np.isfinite(rad).all() and (rad[:, 0] >= 0).all()


def test_rt_amr_multigroup_he_matches_uniform():
    """rt_ngroups=3 + helium on a levelmin==levelmax hierarchy tracks
    the uniform driver's 3-ion ladder (same SED-averaged groups, same
    chemistry; ``rt/rt_spectra.f90`` + ``rt_cooling_module.f90``)."""
    from ramses_tpu.driver import Simulation

    tend = 0.004
    g = _rt_groups(4, 4, tend=tend)
    g["rt_params"]["rt_ngroups"] = 3
    g["rt_params"]["rt_y_he"] = 0.25
    g["rt_params"]["rt_t_star"] = 1e5
    asim = AmrSim(params_from_dict({k: dict(v) for k, v in g.items()},
                                   ndim=3), dtype=jnp.float64)
    assert asim.rt_amr.full3 and asim.rt_amr.ng == 3
    asim.evolve(tend, nstepmax=3)
    v_amr = asim.rt_amr.ionized_volume(asim)

    usim = Simulation(params_from_dict(
        {k: dict(v) for k, v in g.items()}, ndim=3), dtype=jnp.float64)
    usim.evolve()
    x_uni = np.asarray(usim.rt.sim.x)
    v_uni = float(x_uni.sum()) * usim.dx ** 3
    assert v_amr > 0.05 and v_uni > 0.05
    assert abs(v_amr - v_uni) < 0.35 * max(v_amr, v_uni), (v_amr, v_uni)
    # the hard photons ionize helium too: He fractions moved off their
    # initial values and stay physical
    l = asim.lmin
    xhe = np.asarray(asim.rt_amr.xhe[l])
    assert np.isfinite(xhe).all()
    assert float(xhe[:, 0].max()) > 1e-3            # HeII formed
    assert (xhe >= 0).all() and (xhe.sum(axis=1) <= 1.0 + 1e-6).all()


def test_rt_amr_multigroup_refined_front():
    """The multigroup/He system on a refined hierarchy: the I-front
    sweeps outward on the fine level and every group's radiation state
    survives regrid migration."""
    refine = {"r_refine": [0.15] * 8, "x_refine": [0.5] * 8,
              "y_refine": [0.5] * 8, "z_refine": [0.5] * 8}
    g = _rt_groups(4, 5, heating=True, refine=refine, tend=0.001)
    g["init_params"]["d_region"] = [10.0]
    g["rt_params"]["rt_ndot"] = 1e44
    g["rt_params"]["rt_ngroups"] = 2
    g["rt_params"]["rt_y_he"] = 0.25
    g["rt_params"]["rt_t_star"] = 1e5
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    assert sim.tree.noct(5) > 0 and sim.rt_amr.ng == 2
    v0 = sim.rt_amr.ionized_volume(sim)
    e0 = sim.totals()[4]
    sim.evolve(0.001, nstepmax=2)
    assert sim.rt_amr.ionized_volume(sim) > 1.5 * v0
    assert sim.totals()[4] > e0                    # photoheated
    for l in sim.levels():
        rad = np.asarray(sim.rt_amr.rad[l])
        assert rad.shape[1] == 2 * 4               # 2 groups x (N, F)
        assert np.isfinite(rad).all()
        assert (rad[:, ::4] >= 0).all()            # every group's N
        xhe = np.asarray(sim.rt_amr.xhe[l])
        assert np.isfinite(xhe).all() and (xhe >= 0).all()


def test_photon_conservation_on_refined_front():
    """Quantify the photon budget on a 2-level hierarchy (VERDICT r3:
    the RT coarse-fine coupling is first-order; pin its conservation
    error).  Optically thin gas + central source: leaf-summed photons
    must match the injected count within a few percent."""
    g = _rt_groups(4, 5, tend=0.01,
                   refine={"r_refine": [-1.0, -1.0, -1.0, 0.25],
                           "x_refine": [0.0, 0.0, 0.0, 0.5],
                           "y_refine": [0.0, 0.0, 0.0, 0.5],
                           "z_refine": [0.0, 0.0, 0.0, 0.5]})
    g["init_params"]["d_region"] = [1e-12]     # optically thin
    p = params_from_dict({k: dict(v) for k, v in g.items()}, ndim=3)
    sim = AmrSim(p, dtype=jnp.float64)
    assert len(sim.levels()) == 2              # source sits in L5 patch
    rt = sim.rt_amr
    dt_code = 2e-3
    nstep = 4
    for _ in range(nstep):
        rt.advance(sim, dt_code)
    total = 0.0
    for l in sim.levels():
        m = sim.maps[l]
        nc = m.noct * 2 ** sim.cfg.ndim
        leaf = ~sim.tree.refined_mask(l)
        vol = (sim.dx(l) * rt.un.scale_l) ** sim.cfg.ndim
        N = np.asarray(rt.rad[l][:nc, 0])[leaf]
        total += float(N.sum() * vol)
    injected = float(p.rt.rt_ndot) * nstep * dt_code * rt.un.scale_t
    assert abs(total - injected) / injected < 0.05, (total, injected)


def test_sink_rt_hii_feedback():
    """Sink RT (HII) feedback (``pm/sink_rt_feedback.f90`` role): a
    sink-fed stellar object injects Vacca+96 ionizing photons at the
    sink's cell.  Optically thin budget closes within 5% (the r04
    photon-budget pin), and with real gas an HII region forms around
    the sink."""
    from ramses_tpu.pm.sinks import SinkSet
    from ramses_tpu.pm.stellar import StellarSet, StellarSpec

    def make_sim(dens):
        g = _rt_groups(4, 5, tend=0.01,
                       refine={"r_refine": [-1.0, -1.0, -1.0, 0.25],
                               "x_refine": [0.0, 0.0, 0.0, 0.5],
                               "y_refine": [0.0, 0.0, 0.0, 0.5],
                               "z_refine": [0.0, 0.0, 0.0, 0.5]})
        g["init_params"]["d_region"] = [dens]
        g["rt_params"]["rt_ndot"] = 0.0          # sink photons only
        p = params_from_dict({k: dict(v) for k, v in g.items()}, ndim=3)
        sim = AmrSim(p, dtype=jnp.float64)
        # hand-place one sink with one 40-Msun stellar object at the
        # box centre (creation/accretion paths are tested elsewhere)
        sim.sinks = SinkSet(x=np.array([[0.5, 0.5, 0.5]]),
                            v=np.zeros((1, 3)), m=np.array([1.0]),
                            tform=np.array([0.0]),
                            idp=np.array([7], np.int64), next_id=8)
        sim.stellar = StellarSet(
            m=np.array([40.0]), tform=np.array([0.0]),
            tlife=np.array([1e30]), x=np.array([[0.5, 0.5, 0.5]]),
            sink_idp=np.array([7], np.int64),
            idp=np.array([1], np.int64))
        sim.stellar_spec = StellarSpec(enabled=True, hii_t_myr=1e6)
        return sim

    # --- budget: optically thin, leaf-summed photons == S(M)*t -------
    sim = make_sim(1e-12)
    rt = sim.rt_amr
    dt_code, nstep = 2e-3, 4
    for _ in range(nstep):
        rt.advance(sim, dt_code)
    assert rt._sink_src, "sink source list never built"
    total = 0.0
    for l in sim.levels():
        m = sim.maps[l]
        nc = m.noct * 2 ** sim.cfg.ndim
        leaf = ~sim.tree.refined_mask(l)
        vol = (sim.dx(l) * rt.un.scale_l) ** sim.cfg.ndim
        total += float(np.asarray(rt.rad[l][:nc, 0])[leaf].sum() * vol)
    sp = sim.stellar_spec
    S = sp.stf_k * (40.0 / sp.stf_m0) ** sp.stf_a \
        / (1.0 + (40.0 / sp.stf_m0) ** sp.stf_b) ** sp.stf_c
    injected = S * nstep * dt_code * rt.un.scale_t
    assert injected > 0
    assert abs(total - injected) / injected < 0.05, (total, injected)

    # --- HII region: real gas ionizes around the sink ----------------
    sim = make_sim(1.0)
    rt = sim.rt_amr
    for _ in range(3):
        rt.advance(sim, 1e-3)
    lmax = max(sim.levels())
    x = np.asarray(rt.xion[lmax])[:sim.maps[lmax].noct * 8]
    xc = sim.tree.cell_centers(lmax, sim.boxlen)
    rr = np.sqrt(((xc - 0.5) ** 2).sum(axis=1))
    near = x[:len(xc)][rr < 0.05].mean()
    assert near > 0.9, near                   # HII around the sink
