"""Fleet hardening (`ramses_tpu/ensemble/{queue,breaker,fsck}` +
`ramses_tpu/resilience/diskguard`).

Pins the tentpole contracts of the multi-host hardening PR:

  * fenced claims — a reclaimed (zombie) worker's every queue write
    raises :class:`FenceLost` and leaves a durable ``stage="fenced"``
    failure_log entry; a zombie-reclaim race completes EXACTLY once
    and the surviving result is bitwise identical to an uninterrupted
    run;
  * ``queue_fsck`` detects and repairs every crash-consistency class
    (torn tmp, orphan heartbeat, dead running claim, duplicate id,
    half-staged result, orphan parked) — ``--check`` exits 0 on a
    clean queue and nonzero on each corruption;
  * the poison-config circuit breaker trips on cross-worker repeats
    of the same config+stage, parks matching queued jobs, and
    half-opens one probe on reset/TTL;
  * disk-pressure degradation — soft watermark sheds checkpoints,
    hard pauses claims, ENOSPC is absorbed (the worker survives);
  * drain/backoff plumbing: requeue backoff gates claims without
    idle-exiting a worker, and skew-biased heartbeats alone cannot
    false-trip a reclaim.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.ensemble import breaker as bkr
from ramses_tpu.ensemble import fsck as qfsck
from ramses_tpu.ensemble import queue as jq
from ramses_tpu.ensemble import service as svc
from ramses_tpu.ensemble.batch import EnsembleEngine, EnsembleSpec
from ramses_tpu.ensemble.service import serve
from ramses_tpu.resilience import faultinject as fi
from ramses_tpu.resilience.diskguard import DiskGuard, guarded_save

pytestmark = pytest.mark.smoke

_MB = 1024 * 1024

#: 2D Sedov ensemble, 2 members, 4 chunks of 2 steps — the smallest
#: job with enough chunk-beats for a mid-run zombie handover
FLEET_NML = "\n".join([
    "&RUN_PARAMS", "hydro=.true.", "nstepmax=8", "/",
    "&AMR_PARAMS", "levelmin=4", "levelmax=4", "boxlen=1.0", "/",
    "&INIT_PARAMS", "nregion=2",
    "region_type(1)='square'", "region_type(2)='point'",
    "x_center=0.5,0.5", "y_center=0.5,0.5",
    "length_x=10.0,1.0", "length_y=10.0,1.0",
    "exp_region=10.0,10.0", "d_region=1.0,0.0", "p_region=1e-5,0.1", "/",
    "&HYDRO_PARAMS", "gamma=1.4", "riemann='hllc'", "/",
    "&OUTPUT_PARAMS", "tend=1e9", "/",
    "&ENSEMBLE_PARAMS", "nmember=2", "perturb_amp=0.01",
    "chunk_steps=2", "/",
])


class _CapTel:
    def __init__(self):
        self.events = []

    def record_event(self, kind, **fields):
        self.events.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.events]


# ---------------------------------------------------------------------
# fenced claims
# ---------------------------------------------------------------------
def test_fence_refuses_every_zombie_write(tmp_path):
    q = str(tmp_path / "q")
    jid = jq.submit(q, "&RUN_PARAMS\n/", job_id="job-f")
    zombie = jq.claim(q, worker="zombie")
    assert zombie.fence == 1
    jq._age_heartbeat(zombie.path, 3600.0)
    assert jq.reclaim_stale(q, stale_s=300.0, log=None) == 1
    # every worker-side write of the superseded claim is refused and
    # each refusal is durable in the canonical record
    for op in (lambda: jq.heartbeat(zombie),
               lambda: jq.complete(zombie, result={"ok": True}),
               lambda: jq.fail(zombie, error="late"),
               lambda: jq.requeue(zombie, error="late")):
        with pytest.raises(jq.FenceLost):
            op()
    j = jq.job_status(q, jid)
    assert j.state == "queued"         # untouched by the zombie
    stages = [e["stage"] for e in j.record["failure_log"]]
    assert stages[0] == "stale" and stages.count("fenced") == 4
    # the new claim holds the bumped token and works normally
    # (submit=0 -> claim=1 -> reclaim=2 -> re-claim=3)
    fresh = jq.claim(q, worker="healthy")
    assert fresh.fence == 3
    jq.heartbeat(fresh)
    jq.complete(fresh, result={"ok": True})
    assert jq.job_status(q, jid).state == "done"


def test_zombie_reclaim_completes_exactly_once_bitwise(tmp_path):
    """THE chaos pin: worker A claims and goes zombie mid-job; the
    fleet reclaims, worker B resumes from A's checkpoint and
    completes; A's late writes are refused with a durable fenced
    event; the job lands in done/ exactly once and the surviving
    result is bitwise identical to an uninterrupted run."""
    q = str(tmp_path / "q")
    jid = jq.submit(q, FLEET_NML, ndim=2, dtype="float64")
    zjob = jq.claim(q, worker="zombie")
    params, rdir, _ = svc._job_setup(q, zjob, log=lambda *a: None)
    spec = EnsembleSpec.from_params(params)
    eng = EnsembleEngine(spec, dtype=jnp.float64)
    for _ in range(2):                 # steps 1..4 of 8, with beats
        eng.finish_chunk(eng.begin_chunk())
        jq.heartbeat(zjob)
        eng.save(rdir)
    # ... the zombie stalls past the staleness timeout
    jq._age_heartbeat(zjob.path, 3600.0)
    counts = serve(q, worker="healthy", idle_exit=True, max_attempts=3,
                   log=lambda *a: None)
    assert counts == {"done": 1, "failed": 0, "requeued": 0}
    # the zombie wakes and tries to keep going: refused, twice
    with pytest.raises(jq.FenceLost):
        jq.heartbeat(zjob)
    with pytest.raises(jq.FenceLost):
        jq.complete(zjob, result={"from": "zombie"})
    done = [n for n in os.listdir(os.path.join(q, "done"))
            if n.endswith(".json")]
    assert done == [jid + ".json"]     # exactly once
    j = jq.job_status(q, jid)
    assert j.record["attempts"] == 2 and j.record["fence"] == 3
    stages = [e["stage"] for e in j.record["failure_log"]]
    assert stages[0] == "stale" and stages.count("fenced") == 2
    assert j.record["result"].get("from") != "zombie"
    # the refusals are a first-class metric
    from ramses_tpu.obs.metrics import parse, render_queue_metrics
    m = parse(render_queue_metrics(q))
    assert m[("ramses_fenced_writes_total", ())] == 2.0

    # bitwise vs an uninterrupted twin of the same job
    q2 = str(tmp_path / "q2")
    jid2 = jq.submit(q2, FLEET_NML, ndim=2, dtype="float64")
    serve(q2, worker="twin", idle_exit=True, log=lambda *a: None)
    res = j.record["result"]
    res2 = jq.job_status(q2, jid2).record["result"]
    a = np.load(os.path.join(res["snapshot"], "ensemble_state.npz"))
    b = np.load(os.path.join(res2["snapshot"], "ensemble_state.npz"))
    assert a["g0_s0"].tobytes() == b["g0_s0"].tobytes()
    assert a["g0_t"].tobytes() == b["g0_t"].tobytes()


def test_heartbeat_skew_alone_cannot_false_trip_reclaim(tmp_path,
                                                        monkeypatch):
    """A worker whose clock is an hour behind writes heartbeats that
    LOOK ancient by wall stamp — but its hb file mtimes are fresh, and
    staleness requires both signals (plus observer-clock progression)
    to agree.  The fleet must not steal a live worker's claim."""
    monkeypatch.setenv(fi.ENV_VAR, "skew:-3600")
    assert fi.heartbeat_skew() == -3600.0
    q = str(tmp_path / "q")
    jq.submit(q, "&RUN_PARAMS\n/", job_id="job-skew")
    job = jq.claim(q, worker="slow-clock")
    jq.heartbeat(job)
    assert jq.reclaim_stale(q, stale_s=60.0, log=None) == 0
    assert jq.reclaim_stale(q, stale_s=60.0, log=None) == 0
    jq.heartbeat(job)                  # still alive, still safe
    jq.complete(job, result={"ok": True})
    assert jq.job_status(q, "job-skew").state == "done"


# ---------------------------------------------------------------------
# requeue backoff
# ---------------------------------------------------------------------
def test_backoff_gates_claims_without_starving_others(tmp_path):
    q = str(tmp_path / "q")
    jq.submit(q, "&RUN_PARAMS\n/", job_id="job-bounce")
    jq.submit(q, "&RUN_PARAMS\n/", job_id="job-fine")
    job = jq.claim(q, worker="w")
    jq.requeue(job, error="boom", backoff_base_s=30.0,
               backoff_cap_s=60.0)
    rec = jq.job_status(q, "job-bounce").record
    assert rec["not_before_unix"] > time.time() + 10.0
    # the bounced job is skipped, the healthy one still claims FIFO
    nxt = jq.claim(q, worker="w")
    assert nxt.id == "job-fine"
    assert jq.claim(q, worker="w") is None
    # once the gate passes, the bounced job claims again (and the
    # gate stamp is consumed)
    rec["not_before_unix"] = time.time() - 1.0
    jq._write_record(jq.job_status(q, "job-bounce").path, rec)
    again = jq.claim(q, worker="w")
    assert again.id == "job-bounce"
    assert "not_before_unix" not in again.record


def test_backoff_delay_doubles_and_caps():
    d1 = [jq._backoff_delay(1, 2.0, 60.0) for _ in range(20)]
    d4 = [jq._backoff_delay(4, 2.0, 60.0) for _ in range(20)]
    d9 = [jq._backoff_delay(9, 2.0, 60.0) for _ in range(20)]
    assert all(1.0 <= d <= 2.0 for d in d1)
    assert all(8.0 <= d <= 16.0 for d in d4)
    assert all(30.0 <= d <= 60.0 for d in d9)       # capped
    assert jq._backoff_delay(5, 0.0, 60.0) == 0.0   # disabled


# ---------------------------------------------------------------------
# queue fsck
# ---------------------------------------------------------------------
def _corrupt(q, kind):
    """Plant exactly one instance of a corruption class; returns the
    job ids involved."""
    if kind == "torn_tmp":
        with open(os.path.join(q, "queued", "torn.json.tmp"),
                  "w") as f:
            f.write("{")
        return []
    if kind == "orphan_heartbeat":
        with open(os.path.join(q, "running", "ghost.json.hb"),
                  "w") as f:
            f.write("{}")
        return []
    if kind == "dead_running":
        jid = jq.submit(q, "&RUN_PARAMS\n/")
        job = jq.claim(q, worker="dead")
        jq._age_heartbeat(job.path, 3600.0)
        return [jid]
    if kind == "duplicate_id":
        jid = jq.submit(q, "&RUN_PARAMS\n/")
        import shutil
        shutil.copy(os.path.join(q, "queued", jid + ".json"),
                    os.path.join(q, "done", jid + ".json"))
        return [jid]
    if kind == "half_staged":
        jid = jq.submit(q, "&RUN_PARAMS\n/")
        rd = jq.results_dir(q, jid)
        stage = os.path.join(rd, "output_00001.tmp")
        os.makedirs(stage)
        os.utime(stage, (time.time() - 3600,) * 2)
        return [jid]
    if kind == "orphan_parked":
        jid = jq.submit(q, "&RUN_PARAMS\n/")
        os.rename(os.path.join(q, "queued", jid + ".json"),
                  os.path.join(q, "parked", jid + ".json"))
        return [jid]
    raise AssertionError(kind)


@pytest.mark.parametrize("kind", ["torn_tmp", "orphan_heartbeat",
                                  "dead_running", "duplicate_id",
                                  "half_staged", "orphan_parked"])
def test_fsck_detects_and_repairs_each_class(tmp_path, kind):
    q = str(tmp_path / "q")
    jq.init_queue(q)
    code, findings = qfsck.fsck(q, log=None)
    assert code == 0 and findings == []            # clean queue
    _corrupt(q, kind)
    code, findings = qfsck.fsck(q, log=None)
    assert code == 1
    assert [f.kind for f in findings] == [kind]
    code, findings = qfsck.fsck(q, do_repair=True, log=None)
    assert code == 0 and all(f.repaired for f in findings)
    code, findings = qfsck.fsck(q, log=None)
    assert code == 0 and findings == []            # clean again


def test_fsck_repair_semantics(tmp_path):
    q = str(tmp_path / "q")
    jq.init_queue(q)
    # a dead running claim is reclaimed THROUGH the fencing machinery
    (jid,) = _corrupt(q, "dead_running")
    qfsck.fsck(q, do_repair=True, log=None)
    j = jq.job_status(q, jid)
    assert j.state == "queued" and j.record["fence"] == 2
    assert [e["stage"] for e in j.record["failure_log"]] == ["stale"]
    # duplicates keep the most-final copy and quarantine the rest
    (jid2,) = _corrupt(q, "duplicate_id")
    qfsck.fsck(q, do_repair=True, log=None)
    assert jq.job_status(q, jid2).state == "done"
    quar = os.listdir(os.path.join(q, "fsck_quarantine"))
    assert quar == [f"queued__{jid2}.json"]
    # an orphaned parked record (breaker gone) is released to queued
    (jid3,) = _corrupt(q, "orphan_parked")
    qfsck.fsck(q, do_repair=True, log=None)
    assert jq.job_status(q, jid3).state == "queued"


def test_fsck_startup_repairs_only_safe_classes(tmp_path):
    q = str(tmp_path / "q")
    jq.init_queue(q)
    _corrupt(q, "torn_tmp")
    (jid,) = _corrupt(q, "dead_running")
    assert qfsck.startup_repair(q, log=lambda *a: None) == 1
    # the torn tmp is gone; the dead claim is left for the serve
    # loop's reclaim (which owns staleness policy), not startup
    assert not os.path.exists(
        os.path.join(q, "queued", "torn.json.tmp"))
    assert jq.job_status(q, jid).state == "running"


def test_fsck_cli_check_repair_json(tmp_path):
    q = str(tmp_path / "q")
    jq.init_queue(q)
    _corrupt(q, "torn_tmp")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, os.path.join(root, "tools",
                                        "queue_fsck.py"), q]
    out = str(tmp_path / "fsck.json")
    r = subprocess.run(cmd + ["--check", "--json", out],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, (r.stdout, r.stderr)
    rep = json.load(open(out))
    assert [f["kind"] for f in rep["findings"]] == ["torn_tmp"]
    r = subprocess.run(cmd + ["--repair"], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr)
    r = subprocess.run(cmd + ["--check"], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr)


# ---------------------------------------------------------------------
# poison-config circuit breaker
# ---------------------------------------------------------------------
def test_breaker_trips_cross_worker_and_parks(tmp_path):
    q = str(tmp_path / "q")
    jid = jq.submit(q, FLEET_NML, ndim=2)
    twin = jq.submit(q, FLEET_NML, ndim=2)   # same fingerprint
    other = jq.submit(q, FLEET_NML.replace("gamma=1.4", "gamma=1.5"),
                      ndim=2)
    rec = jq.job_status(q, jid).record
    tel = _CapTel()
    # one worker failing twice is NOT poison (min_workers=2) ...
    rec["worker"] = "w1"
    assert not bkr.record_failure(q, rec, "requeue", failures=2,
                                  min_workers=2, telemetry=tel)
    assert not bkr.record_failure(q, rec, "requeue", failures=2,
                                  min_workers=2, telemetry=tel)
    assert bkr.load(q, bkr.fingerprint_of(rec))["state"] == "closed"
    # ... a second worker confirming the same stage IS
    rec["worker"] = "w2"
    assert bkr.record_failure(q, rec, "fail", failures=2,
                              min_workers=2, telemetry=tel)
    fp = bkr.fingerprint_of(rec)
    assert bkr.load(q, fp)["state"] == "open"
    assert "breaker_trip" in tel.kinds()
    # matching queued jobs are parked, different configs are not
    assert jq.job_status(q, jid).state == "parked"
    assert jq.job_status(q, twin).state == "parked"
    assert jq.job_status(q, other).state == "queued"
    parked = jq.job_status(q, twin).record
    assert parked["failure_log"][-1]["stage"] == "breaker"
    # hang and crash count separately: a hang on an open breaker's
    # config doesn't reset anything, and stale/drain/fenced never
    # count at all (exercised via queue._breaker_note)
    assert bkr.breaker_stage("hang") == "hang"
    assert bkr.breaker_stage("requeue") == "crash"

    # half-open releases exactly one probe
    assert bkr.reset(q, fp, log=lambda *a: None) == [fp]
    b = bkr.load(q, fp)
    assert b["state"] == "half_open"
    back = [j for j in (jid, twin)
            if jq.job_status(q, j).state == "queued"]
    assert len(back) == 1
    # a success on the probe closes the breaker and releases the rest
    bkr.on_success(q, rec, telemetry=tel)
    assert bkr.load(q, fp)["state"] == "closed"
    assert jq.job_status(q, jid).state == "queued"
    assert jq.job_status(q, twin).state == "queued"


def test_breaker_half_open_probe_failure_snaps_open(tmp_path):
    q = str(tmp_path / "q")
    jid = jq.submit(q, FLEET_NML, ndim=2)
    rec = jq.job_status(q, jid).record
    rec["worker"] = "w1"
    bkr.record_failure(q, rec, "fail", failures=1, min_workers=1)
    fp = bkr.fingerprint_of(rec)
    bkr.reset(q, fp, log=lambda *a: None)
    assert bkr.load(q, fp)["state"] == "half_open"
    # the probe fails: straight back to open, no threshold debate
    assert bkr.record_failure(q, rec, "fail", failures=99,
                              min_workers=99)
    assert bkr.load(q, fp)["state"] == "open"


def test_breaker_ttl_sweep_half_opens(tmp_path):
    q = str(tmp_path / "q")
    jid = jq.submit(q, FLEET_NML, ndim=2)
    rec = jq.job_status(q, jid).record
    rec["worker"] = "w1"
    bkr.record_failure(q, rec, "fail", failures=1, min_workers=1,
                       ttl_s=0.0)
    fp = bkr.fingerprint_of(rec)
    assert jq.job_status(q, jid).state == "parked"
    assert bkr.sweep(q, log=lambda *a: None) == 1
    assert bkr.load(q, fp)["state"] == "half_open"
    assert jq.job_status(q, jid).state == "queued"   # the probe


def test_serve_trips_breaker_end_to_end(tmp_path, monkeypatch):
    """Two attempts on a namelist the engine rejects trip the breaker
    through the live serve loop; the matching queued twin is parked
    and the CLI reset releases it half-open."""
    monkeypatch.setenv("RAMSES_BREAKER_N", "2")
    monkeypatch.setenv("RAMSES_BREAKER_MIN_WORKERS", "1")
    monkeypatch.setenv("RAMSES_QUEUE_BACKOFF_S", "0")
    q = str(tmp_path / "q")
    bad = FLEET_NML.replace("levelmax=4", "levelmax=5")
    jid = jq.submit(q, bad, ndim=2)
    twin = jq.submit(q, bad, ndim=2)
    counts = serve(q, worker="w1", idle_exit=True, max_attempts=2,
                   order="fifo", log=lambda *a: None)
    assert counts == {"done": 0, "failed": 1, "requeued": 1}
    assert jq.job_status(q, jid).state == "failed"
    assert jq.job_status(q, twin).state == "parked"
    fp = bkr.fingerprint_of(jq.job_status(q, jid).record)
    assert bkr.load(q, fp)["state"] == "open"
    # operator resets via the fsck CLI; the twin is released as probe
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable,
                        os.path.join(root, "tools", "queue_fsck.py"),
                        q, "--reset-breaker", "all"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert bkr.load(q, fp)["state"] == "half_open"
    assert jq.job_status(q, twin).state == "queued"


# ---------------------------------------------------------------------
# disk-pressure degradation
# ---------------------------------------------------------------------
def test_diskguard_watermarks_and_enospc_cooldown():
    free = {"b": 100.0 * _MB}
    tel = _CapTel()
    g = DiskGuard("/tmp", soft_free_bytes=20 * _MB,
                  hard_free_bytes=5 * _MB, probe=lambda p: free["b"])
    assert g.level() == "ok" and g.allow_checkpoint() and \
        g.allow_claim()
    free["b"] = 10.0 * _MB
    assert g.level() == "soft"
    assert not g.allow_checkpoint() and g.allow_claim()
    g.emit(tel, where="beat")
    free["b"] = 2.0 * _MB
    assert g.level() == "hard" and not g.allow_claim()
    g.emit(tel, where="claim")
    free["b"] = 100.0 * _MB
    assert g.level() == "ok"
    g.emit(tel, where="claim")         # recovery edge
    levels = [f["level"] for k, f in tel.events if k == "io_degraded"]
    assert levels == ["soft", "hard", "ok"]        # edges only
    # a real ENOSPC forces soft for the cooldown even if statvfs
    # disagrees (thin-provisioned/quota filesystems lie)
    g.note_enospc()
    assert g.level() == "soft" and not g.allow_checkpoint()


def test_guarded_save_absorbs_enospc_only():
    import errno
    g = DiskGuard("/tmp", probe=lambda p: 1e15)
    ran = []
    assert guarded_save(lambda: ran.append(1), g) is True and ran
    def enospc():
        raise OSError(errno.ENOSPC, "no space left on device")
    assert guarded_save(enospc, g, log=lambda *a: None) is False
    assert g.level() == "soft"         # degraded, not crashed
    assert guarded_save(lambda: ran.append(2), g) is False  # shed
    def eperm():
        raise OSError(errno.EPERM, "nope")
    with pytest.raises(OSError):       # only ENOSPC is absorbed
        guarded_save(eperm, DiskGuard("/tmp", probe=lambda p: 1e15))


def test_serve_pauses_claims_under_hard_pressure(tmp_path,
                                                monkeypatch):
    """Hard watermark: the worker stops CLAIMING but stays alive —
    the queued job is untouched and the worker exits cleanly on
    drain, never by crash or idle-exit."""
    monkeypatch.setenv("RAMSES_DISK_HARD_MB", str(10 ** 9))
    q = str(tmp_path / "q")
    jid = jq.submit(q, FLEET_NML, ndim=2)
    out = {}

    def run():
        out["counts"] = serve(q, worker="parched", idle_exit=True,
                              poll_s=0.02, log=lambda *a: None)

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.5)
    assert jq.job_status(q, jid).state == "queued"   # never claimed
    svc.request_drain()
    t.join(timeout=30)
    assert not t.is_alive()
    assert out["counts"] == {"done": 0, "failed": 0, "requeued": 0}
    wtel = os.path.join(q, "workers", "parched.jsonl")
    kinds = [json.loads(line).get("kind") for line in open(wtel)]
    assert "io_degraded" in kinds and "serve_drain" in kinds


def test_enospc_fault_sheds_checkpoint_but_job_completes(tmp_path):
    """An injected ENOSPC at the step-3 checkpoint degrades (the
    checkpoint is shed, io_degraded recorded) — the run still
    completes and the final snapshot is written."""
    fi.reset_fired()
    nml = FLEET_NML.replace("&RUN_PARAMS",
                            "&RUN_PARAMS\nfault_inject='enospc@3'")
    q = str(tmp_path / "q")
    jid = jq.submit(q, nml, ndim=2, dtype="float64")
    counts = serve(q, worker="t", idle_exit=True, max_attempts=2,
                   log=lambda *a: None)
    assert counts == {"done": 1, "failed": 0, "requeued": 0}
    job = jq.job_status(q, jid)
    assert job.record["attempts"] == 1         # no retry burned
    res = job.record["result"]
    kinds = [json.loads(line).get("kind")
             for line in open(res["telemetry"])]
    assert "io_degraded" in kinds and "ensemble_done" in kinds
    assert os.path.isfile(os.path.join(res["snapshot"],
                                       "ensemble_state.npz"))


# ---------------------------------------------------------------------
# fault injection + supervisor plumbing
# ---------------------------------------------------------------------
def test_faultinject_parses_fleet_faults():
    faults, _ = fi._parse("zombie@2,enospc@3,skew:5.5,nan@1:member=0")
    assert ("zombie", 2) in faults and ("enospc", 3) in faults
    assert ("skew", 5.5) in faults


def test_faultinject_zombie_and_enospc_fire_once(monkeypatch):
    import errno
    fi.reset_fired()
    monkeypatch.setenv("RAMSES_ZOMBIE_SLEEP_S", "0.05")
    inj = fi.FaultInjector("zombie@1")
    assert inj.maybe_zombie(1) is False      # strict arming: too late
    inj = fi.FaultInjector("zombie@1")
    inj.maybe_zombie(0)
    t0 = time.monotonic()
    assert inj.maybe_zombie(1) is True
    assert time.monotonic() - t0 >= 0.05
    inj = fi.FaultInjector("zombie@1")
    inj.maybe_zombie(0)
    assert inj.maybe_zombie(1) is False      # once per process
    inj = fi.FaultInjector("enospc@2")
    inj.observe(0)
    with pytest.raises(OSError) as ei:
        inj.maybe_enospc(2)
    assert ei.value.errno == errno.ENOSPC
    inj = fi.FaultInjector("enospc@2")
    inj.observe(0)
    inj.maybe_enospc(5)                      # once per process
    fi.reset_fired()


def test_supervise_escalates_caller_exceptions(tmp_path):
    from ramses_tpu.resilience.supervisor import supervise

    class Escape(Exception):
        pass

    params = None
    builds = []

    def build(restart):
        builds.append(restart)
        return object()

    def drive(sim):
        raise Escape("caller control flow")

    # without escalate the supervisor would burn retries; with it the
    # exception re-raises immediately after ONE build
    from ramses_tpu.config import params_from_dict
    params = params_from_dict({"run_params": {"nstepmax": 1}}, ndim=1)
    with pytest.raises(Escape):
        supervise(build, drive, params, base_dir=str(tmp_path),
                  max_attempts=3, backoff_s=0.0,
                  log=lambda *a: None, escalate=(Escape,))
    assert len(builds) == 1


# ---------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------
def test_metrics_expose_breaker_and_disk_families(tmp_path):
    from ramses_tpu.obs.metrics import parse, render_queue_metrics
    q = str(tmp_path / "q")
    jid = jq.submit(q, FLEET_NML, ndim=2)
    rec = jq.job_status(q, jid).record
    rec["worker"] = "w1"
    bkr.record_failure(q, rec, "fail", failures=1, min_workers=1)
    fp = bkr.fingerprint_of(rec)
    m = parse(render_queue_metrics(q))
    assert m[("ramses_breaker_state",
              (("fp", fp), ("stage", "crash")))] == 2.0   # open
    assert m[("ramses_queue_jobs", (("state", "parked"),))] == 1.0
    assert m[("ramses_fenced_writes_total", ())] == 0.0
    disk = [v for (name, _), v in m.items()
            if name == "ramses_disk_free_bytes"]
    assert disk and disk[0] > 0
