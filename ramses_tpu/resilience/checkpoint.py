"""Atomic validated checkpoints: manifest + staged rename + scanning.

The reference restarts from whatever ``output_NNNNN/`` it finds
(``nrestart>0``); a job killed mid-dump leaves a directory that parses
until a reader hits the truncation.  Here every dump is staged into
``output_NNNNN.tmp/``, every file is fsynced and hashed into a
``manifest.json``, and only then does one ``os.replace`` make the
checkpoint visible — readers either see a complete validated directory
or nothing.  ``validate_checkpoint`` re-checks the manifest against
the bytes on disk, so auto-resume (``resolve_restart_dir``) can skip
bit-rotted or truncated checkpoints with a logged reason instead of
crashing into them.

Elastic sharded checkpoints (``io/pario.py`` format 2) add one level
of hierarchy: each writer commits a ``shard_SSSSS/`` subdirectory
carrying its own schema-1 manifest, and the GLOBAL manifest
(:func:`write_global_manifest`) records every shard manifest's hash
under a ``shards`` table — a two-phase commit where phase 1 is each
shard validating its own bytes and phase 2 is one process sealing the
set.  ``validate_checkpoint`` recurses through the shard table, so a
checkpoint with a missing, torn, or quarantined shard never scans as
valid; :func:`quarantine_shard` renames a corrupt shard aside (with a
durable reason) so the scanner's fallback-to-next-oldest logic applies
to shard-level rot exactly as it does to whole-checkpoint rot.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Callable, Dict, List, Optional, Tuple

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = 1


def _fsync_path(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def write_manifest(stage_dir: str, meta: Optional[Dict[str, Any]] = None
                   ) -> str:
    """Hash + size every file under ``stage_dir`` (recursively) into
    ``manifest.json``, fsync it and the directory.  Returns the
    manifest path."""
    files: Dict[str, Dict[str, Any]] = {}
    for root, _dirs, names in os.walk(stage_dir):
        for name in sorted(names):
            if name == MANIFEST_NAME:
                continue
            p = os.path.join(root, name)
            rel = os.path.relpath(p, stage_dir)
            files[rel] = {"size": os.path.getsize(p), "sha256": _sha256(p)}
            _fsync_path(p)
    mpath = os.path.join(stage_dir, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump({"schema": MANIFEST_SCHEMA,
                   "meta": dict(meta or {}),
                   "files": files}, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(stage_dir)
    return mpath


def write_global_manifest(stage_dir: str,
                          meta: Optional[Dict[str, Any]] = None,
                          shard_prefix: str = "shard_") -> str:
    """Phase-2 manifest for an elastic sharded checkpoint: hash the
    TOP-LEVEL files of ``stage_dir`` into the usual ``files`` table and
    seal every committed ``shard_*/`` subdirectory into a ``shards``
    table keyed on the shard's own (already fsynced) manifest hash —
    the global manifest validates iff every shard manifest is the one
    its writer staged.  Raises if any shard lacks a readable manifest:
    the caller must never seal a checkpoint with an unvalidated shard.
    """
    files: Dict[str, Dict[str, Any]] = {}
    shards: Dict[str, Dict[str, Any]] = {}
    for name in sorted(os.listdir(stage_dir)):
        p = os.path.join(stage_dir, name)
        if os.path.isdir(p):
            if not name.startswith(shard_prefix):
                continue
            smpath = os.path.join(p, MANIFEST_NAME)
            try:
                with open(smpath) as f:
                    smeta = dict(json.load(f).get("meta") or {})
            except (OSError, json.JSONDecodeError) as e:
                raise RuntimeError(
                    f"write_global_manifest: shard {name} has no "
                    f"readable manifest ({e}); commit refused")
            ent: Dict[str, Any] = {
                "manifest_size": os.path.getsize(smpath),
                "manifest_sha256": _sha256(smpath)}
            # summary columns the elastic reader needs without opening
            # shard manifests: row intervals, oct/particle counts, the
            # Hilbert-order key range
            for k in ("shard", "process", "rows", "octs", "npart",
                      "key_range"):
                if k in smeta:
                    ent[k] = smeta[k]
            shards[name] = ent
        elif name != MANIFEST_NAME:
            files[name] = {"size": os.path.getsize(p),
                           "sha256": _sha256(p)}
            _fsync_path(p)
    mpath = os.path.join(stage_dir, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump({"schema": MANIFEST_SCHEMA,
                   "meta": dict(meta or {}),
                   "files": files,
                   "shards": shards}, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(stage_dir)
    return mpath


def finalize_checkpoint(stage_dir: str, final_dir: str,
                        meta: Optional[Dict[str, Any]] = None) -> str:
    """Manifest the staged directory and atomically rename it into
    place.  A pre-existing ``final_dir`` is REMOVED first (replaced,
    never merged — the stale same-iout mixing hazard), and the parent
    directory is fsynced so the rename survives a crash."""
    write_manifest(stage_dir, meta)
    if os.path.isdir(final_dir):
        shutil.rmtree(final_dir)
    os.replace(stage_dir, final_dir)
    parent = os.path.dirname(os.path.abspath(final_dir))
    try:
        _fsync_path(parent)
    except OSError:
        pass                      # e.g. parent on a non-fsyncable mount
    return final_dir


def validate_checkpoint(outdir: str,
                        verify_hash: bool = True) -> Tuple[bool, str]:
    """(ok, reason): does ``outdir`` hold a complete checkpoint whose
    bytes match its manifest?  ``verify_hash=False`` checks existence
    and sizes only (cheap scan mode)."""
    mpath = os.path.join(outdir, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        return False, "no manifest.json (pre-atomic or partial dump)"
    try:
        with open(mpath) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return False, f"unreadable manifest: {e}"
    if man.get("schema") != MANIFEST_SCHEMA:
        return False, f"unknown manifest schema {man.get('schema')!r}"
    files = man.get("files")
    if not isinstance(files, dict):
        return False, "manifest has no file table"
    for rel, ent in files.items():
        p = os.path.join(outdir, rel)
        if not os.path.isfile(p):
            return False, f"missing file {rel}"
        if os.path.getsize(p) != int(ent.get("size", -1)):
            return False, f"size mismatch on {rel}"
        if verify_hash and _sha256(p) != ent.get("sha256"):
            return False, f"checksum mismatch on {rel}"
    shards = man.get("shards")
    if isinstance(shards, dict):
        for name, ent in shards.items():
            ok, reason = validate_shard(outdir, name, ent,
                                        verify_hash=verify_hash)
            if not ok:
                return False, reason
    return True, "ok"


def validate_shard(outdir: str, name: str, ent: Dict[str, Any],
                   verify_hash: bool = True) -> Tuple[bool, str]:
    """(ok, reason) for one shard of an elastic checkpoint: the shard
    dir exists, its manifest is byte-identical to what the global
    commit sealed (always hash-checked — the manifest is tiny), and
    the shard's own file table validates (sizes always; payload hashes
    when ``verify_hash``)."""
    sdir = os.path.join(outdir, name)
    if not os.path.isdir(sdir):
        return False, f"missing shard {name}"
    smpath = os.path.join(sdir, MANIFEST_NAME)
    if not os.path.isfile(smpath):
        return False, f"shard {name} has no manifest"
    if os.path.getsize(smpath) != int(ent.get("manifest_size", -1)) \
            or _sha256(smpath) != ent.get("manifest_sha256"):
        return False, f"shard {name} manifest mismatch"
    ok, reason = validate_checkpoint(sdir, verify_hash=verify_hash)
    if not ok:
        return False, f"shard {name}: {reason}"
    return True, "ok"


def quarantine_shard(outdir: str, name: str, reason: str,
                     log: Optional[Callable] = print) -> Optional[str]:
    """Rename a corrupt ``shard_*`` dir to ``<name>.quarantined`` and
    record the reason inside it.  The parent checkpoint then fails
    validation (missing shard), so every scanner falls back to the
    next-oldest globally-valid checkpoint — shard rot degrades to the
    whole-checkpoint rot path.  Returns the quarantine path (None when
    the shard is already gone)."""
    src = os.path.join(outdir, name)
    if not os.path.isdir(src):
        return None
    dst = src + ".quarantined"
    if os.path.isdir(dst):
        shutil.rmtree(dst, ignore_errors=True)
    os.replace(src, dst)
    try:
        with open(os.path.join(dst, "quarantine.json"), "w") as f:
            json.dump({"reason": reason, "shard": name}, f, indent=1)
    except OSError:
        pass
    if log is not None:
        log(f"resilience: quarantined {os.path.basename(outdir)}/"
            f"{name}: {reason}")
    return dst


def read_manifest_meta(outdir: str) -> Dict[str, Any]:
    """The manifest's ``meta`` block ({} when absent/unreadable)."""
    try:
        with open(os.path.join(outdir, MANIFEST_NAME)) as f:
            return dict(json.load(f).get("meta") or {})
    except (OSError, json.JSONDecodeError):
        return {}


def read_quarantine_census(outdir: str) -> Dict[int, Dict[str, Any]]:
    """Per-member quarantine census from an ensemble checkpoint's
    manifest meta: ``{member: {reason, nstep, t, dump}}`` ({} when the
    checkpoint predates member isolation or nothing is quarantined).
    Written by ``EnsembleEngine.save`` whenever the batched step-guard
    evicted members — the durable record of *which* members' results
    in this checkpoint are last-clean-state rather than completed."""
    census = read_manifest_meta(outdir).get("quarantined") or {}
    return {int(k): dict(v) for k, v in census.items()}


CHECKPOINT_PREFIXES = ("output_", "pario_")


def scan_checkpoints(base_dir: str, log: Optional[Callable] = None,
                     prefix=CHECKPOINT_PREFIXES
                     ) -> List[Tuple[str, Dict[str, Any]]]:
    """Manifest-valid checkpoints under ``base_dir``, newest first by
    (nstep, t, iout) — so an emergency dump (high iout, current step)
    correctly outranks an older scheduled output.  Invalid candidates
    are skipped with a logged reason.  ``prefix`` may be one prefix or
    a tuple; the default covers both snapshot (``output_``) and elastic
    pario (``pario_``) checkpoints — a staged ``pario_NNNNN.tmp``
    fails the all-digits suffix check, so a dump killed mid-commit is
    never a candidate."""
    prefixes = (prefix,) if isinstance(prefix, str) else tuple(prefix)
    try:
        names = sorted(os.listdir(base_dir))
    except OSError:
        return []
    found = []
    for name in names:
        if not any(name.startswith(p) and name[len(p):].isdigit()
                   for p in prefixes):
            continue
        outdir = os.path.join(base_dir, name)
        if not os.path.isdir(outdir):
            continue
        ok, reason = validate_checkpoint(outdir)
        if not ok:
            if log is not None:
                log(f"resilience: skipping {name}: {reason}")
            continue
        meta = read_manifest_meta(outdir)
        found.append((outdir, meta))
    found.sort(key=lambda e: (int(e[1].get("nstep", 0)),
                              float(e[1].get("t", 0.0)),
                              int(e[1].get("iout", 0))),
               reverse=True)
    return found


def latest_valid_checkpoint(base_dir: str,
                            log: Optional[Callable] = print
                            ) -> Optional[str]:
    """Newest manifest-valid ``output_NNNNN``/``pario_NNNNN`` under
    ``base_dir`` (by stored nstep/t, not by directory number), or
    None."""
    found = scan_checkpoints(base_dir, log=log)
    return found[0][0] if found else None


def rotate_checkpoints(base_dir: str, keep: int,
                       protect: Optional[str] = None):
    """Remove the oldest manifest-valid checkpoints beyond ``keep``.
    Only validated checkpoints are rotation candidates — pre-atomic
    output dirs (science products without manifests) are never
    touched.  ``protect`` is exempt regardless of age."""
    if keep <= 0:
        return
    found = scan_checkpoints(base_dir, log=None)
    prot = os.path.abspath(protect) if protect else None
    for outdir, _meta in found[keep:]:
        if prot and os.path.abspath(outdir) == prot:
            continue
        shutil.rmtree(outdir, ignore_errors=True)


def scrub_checkpoints(base_dir: str,
                      log: Optional[Callable] = print
                      ) -> List[Tuple[str, str]]:
    """Quarantine invalid checkpoints under ``base_dir`` by renaming
    them to ``<name>.corrupt`` — used by the run service before a
    resume so a checkpoint that rotted between beats cannot wedge the
    auto-resume scan loop.  Only directories that CARRY a manifest and
    fail validation are touched; pre-atomic science outputs (no
    manifest) are never candidates.  Returns ``[(path, reason), ...]``
    for everything moved."""
    try:
        names = sorted(os.listdir(base_dir))
    except OSError:
        return []
    moved = []
    for name in names:
        if not any(name.startswith(p) and name[len(p):].isdigit()
                   for p in CHECKPOINT_PREFIXES):
            continue
        outdir = os.path.join(base_dir, name)
        if not os.path.isdir(outdir) or not os.path.isfile(
                os.path.join(outdir, MANIFEST_NAME)):
            continue
        ok, reason = validate_checkpoint(outdir)
        if ok:
            continue
        dst = outdir + ".corrupt"
        if os.path.isdir(dst):
            shutil.rmtree(dst, ignore_errors=True)
        os.replace(outdir, dst)
        if log is not None:
            log(f"resilience: scrub quarantined {name}: {reason}")
        moved.append((dst, reason))
    return moved


def resolve_restart_dir(params, base_dir: Optional[str] = None,
                        log: Optional[Callable] = print
                        ) -> Optional[str]:
    """The checkpoint directory a run should restore from, or None for
    a fresh start.

    ``nrestart > 0``: the explicit ``output_NNNNN`` (missing → error;
    a manifest that fails validation → error — restarting from known
    corruption must be loud; a pre-manifest directory passes with a
    warning for backward compatibility).  ``nrestart == -1`` or
    ``auto_resume=.true.``: newest manifest-valid checkpoint, or None
    when there is none yet (first launch of a supervised run)."""
    run = getattr(params, "run", None)
    nrestart = int(getattr(run, "nrestart", 0))
    auto = bool(getattr(run, "auto_resume", False)) or nrestart == -1
    base = base_dir if base_dir is not None else str(
        getattr(getattr(params, "output", None), "output_dir", "."))
    if nrestart > 0:
        outdir = os.path.join(base, f"output_{nrestart:05d}")
        if not os.path.isdir(outdir):
            raise FileNotFoundError(
                f"nrestart={nrestart}: {outdir} does not exist")
        if os.path.isfile(os.path.join(outdir, MANIFEST_NAME)):
            ok, reason = validate_checkpoint(outdir)
            if not ok:
                raise RuntimeError(
                    f"nrestart={nrestart}: {outdir} fails validation "
                    f"({reason}); use nrestart=-1 to auto-select the "
                    "newest valid checkpoint instead")
        elif log is not None:
            log(f"resilience: {outdir} has no manifest (pre-atomic "
                "dump); restoring without validation")
        return outdir
    if auto:
        out = latest_valid_checkpoint(base, log=log)
        if out is not None and log is not None:
            log(f"resilience: auto-resume from {out}")
        return out
    return None
