"""Integer Morton (Z-order) keys for oct coordinates.

Replaces the reference's Hilbert state-machine keys (``amr/hilbert.f90:5-196``)
for *topology bookkeeping*: the tree only needs a total order with fast
encode/decode and uniqueness, which bit-interleaved int64 Morton codes give
without the reference's ``real*16 QUADHILBERT`` workaround (its level cap —
19 in 3D — came from squeezing keys into floats; int64 Morton supports 21
bits/dim in 3D).  Hilbert ordering still matters for *domain decomposition*
locality and is provided separately (``parallel/``); within a single host the
sorted Morton array is the whole "tree": membership = ``searchsorted``.
"""

from __future__ import annotations

import numpy as np


def _spread2(x: np.ndarray) -> np.ndarray:
    """Spread bits of x (< 2^31) with 1 zero between (2D interleave)."""
    x = x.astype(np.uint64)
    x = (x | (x << np.uint64(16))) & np.uint64(0x0000FFFF0000FFFF)
    x = (x | (x << np.uint64(8))) & np.uint64(0x00FF00FF00FF00FF)
    x = (x | (x << np.uint64(4))) & np.uint64(0x0F0F0F0F0F0F0F0F)
    x = (x | (x << np.uint64(2))) & np.uint64(0x3333333333333333)
    x = (x | (x << np.uint64(1))) & np.uint64(0x5555555555555555)
    return x


def _spread3(x: np.ndarray) -> np.ndarray:
    """Spread bits of x (< 2^21) with 2 zeros between (3D interleave)."""
    x = x.astype(np.uint64) & np.uint64(0x1FFFFF)
    x = (x | (x << np.uint64(32))) & np.uint64(0x1F00000000FFFF)
    x = (x | (x << np.uint64(16))) & np.uint64(0x1F0000FF0000FF)
    x = (x | (x << np.uint64(8))) & np.uint64(0x100F00F00F00F00F)
    x = (x | (x << np.uint64(4))) & np.uint64(0x10C30C30C30C30C3)
    x = (x | (x << np.uint64(2))) & np.uint64(0x1249249249249249)
    return x


def encode(ig: np.ndarray, ndim: int) -> np.ndarray:
    """Morton keys (int64) from integer coords ``ig [n, ndim]``."""
    ig = np.asarray(ig)
    if ndim == 1:
        return ig[:, 0].astype(np.int64)
    if len(ig) >= 4096:      # amortize the ctypes call
        from ramses_tpu import native
        nat = native.morton_encode(ig, ndim)
        if nat is not None:
            return nat
    if ndim == 2:
        return (_spread2(ig[:, 0]) | (_spread2(ig[:, 1]) << np.uint64(1))
                ).astype(np.int64)
    return (_spread3(ig[:, 0]) | (_spread3(ig[:, 1]) << np.uint64(1))
            | (_spread3(ig[:, 2]) << np.uint64(2))).astype(np.int64)


def _compact2(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint64) & np.uint64(0x5555555555555555)
    x = (x | (x >> np.uint64(1))) & np.uint64(0x3333333333333333)
    x = (x | (x >> np.uint64(2))) & np.uint64(0x0F0F0F0F0F0F0F0F)
    x = (x | (x >> np.uint64(4))) & np.uint64(0x00FF00FF00FF00FF)
    x = (x | (x >> np.uint64(8))) & np.uint64(0x0000FFFF0000FFFF)
    x = (x | (x >> np.uint64(16))) & np.uint64(0x00000000FFFFFFFF)
    return x


def _compact3(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint64) & np.uint64(0x1249249249249249)
    x = (x | (x >> np.uint64(2))) & np.uint64(0x10C30C30C30C30C3)
    x = (x | (x >> np.uint64(4))) & np.uint64(0x100F00F00F00F00F)
    x = (x | (x >> np.uint64(8))) & np.uint64(0x1F0000FF0000FF)
    x = (x | (x >> np.uint64(16))) & np.uint64(0x1F00000000FFFF)
    x = (x | (x >> np.uint64(32))) & np.uint64(0x1FFFFF)
    return x


def decode(keys: np.ndarray, ndim: int) -> np.ndarray:
    """Integer coords ``[n, ndim]`` from Morton keys."""
    k = np.asarray(keys).astype(np.uint64)
    if ndim == 1:
        return k.astype(np.int64)[:, None]
    if ndim == 2:
        return np.stack([_compact2(k), _compact2(k >> np.uint64(1))],
                        axis=1).astype(np.int64)
    return np.stack([_compact3(k), _compact3(k >> np.uint64(1)),
                     _compact3(k >> np.uint64(2))], axis=1).astype(np.int64)
