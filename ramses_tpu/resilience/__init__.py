"""Fault-tolerant execution layer (ISSUE 5, SURVEY.md resilience).

Three pillars, each independently usable:

  1. **Atomic validated checkpoints** (:mod:`.checkpoint`):
     ``dump_all``/``dump_pario`` stage into ``output_NNNNN.tmp/``,
     fsync, write a ``manifest.json`` (per-file SHA-256 + sizes +
     nstep/t/dt metadata), then ``os.replace``-rename to the final
     name — a kill -9 mid-dump can never leave a directory that
     validates as a checkpoint.  ``keep_last``-N rotation removes old
     manifest-valid outputs only.

  2. **Auto-resume** (:mod:`.checkpoint` ``resolve_restart_dir`` +
     :mod:`.supervisor`): ``nrestart=-1`` or ``auto_resume=.true.``
     scans the run directory for the newest manifest-valid checkpoint,
     skipping corrupt/partial ones with a logged reason;
     :func:`supervisor.supervise` wraps build-and-evolve in a bounded
     retry-with-resume loop (exponential backoff) so preemption
     mid-run resumes instead of failing.

  3. **In-run numerical fault recovery** (:mod:`.stepguard`): with
     ``&RUN_PARAMS max_step_retries > 0`` the drivers retain the
     pre-step device state, check the scan-stacked (t, dt) summaries
     they already fetch for finiteness, and on a trip roll back and
     retry with halved dt (the reference's redo-step), escalating the
     Riemann solver to diffusive LLF on the second retry, emergency
     dumping + aborting when the ladder is exhausted.  Zero overhead
     when off: no capture, no extra host↔device fetches.

A fourth pillar spans processes: **elastic sharded checkpoints**
(:mod:`ramses_tpu.io.pario` format 2 + the ``shards`` manifest table
here) — every process commits a validated ``shard_SSSSS/`` under a
two-phase global commit, and the reader re-decomposes the saved
hierarchy onto whatever mesh is CURRENT, quarantining corrupt shards
(:func:`checkpoint.quarantine_shard`) so shard rot falls back to the
next-oldest valid checkpoint like whole-checkpoint rot does.

:mod:`.faultinject` makes all of it deterministically testable
(``&RUN_PARAMS fault_inject`` / env ``RAMSES_FAULT_INJECT``: NaN at
step k, SIGTERM at step k, truncate a checkpoint file, corrupt shard
J's payload mid-commit, kill host J between shard staging and the
global commit).
"""

from ramses_tpu.resilience.checkpoint import (  # noqa: F401
    finalize_checkpoint, latest_valid_checkpoint, quarantine_shard,
    resolve_restart_dir, rotate_checkpoints, scrub_checkpoints,
    validate_checkpoint, validate_shard, write_global_manifest)
from ramses_tpu.resilience.diskguard import (  # noqa: F401
    DiskGuard, guarded_save)
from ramses_tpu.resilience.stepguard import (  # noqa: F401
    StepGuard, StepRetryExhausted)
