"""Two-level parallelism through the engine and the gang service
(``ensemble/meshplan.py`` + ``ensemble/batch.py`` + the cost-aware
serve loop).

Pins the composition contracts:

  * a mesh-of-8 PACKED run (member vmap sharded over per-device
    replicas) is BITWISE the solo per-member runs — the replica axis
    must be numerically invisible, exactly like the vmap axis;
  * a SLAB-mode member is bitwise the standalone sharded sim through
    ``parallel/halo.run_steps_halo``;
  * checkpoints round-trip ACROSS packings (packed -> single and
    single -> packed) bitwise — ensemble checkpoints are elastic over
    the device mesh, not just over host counts;
  * one stacked ``jax.device_get`` per chunk regardless of how many
    sub-batch groups a sweep splits into;
  * the cost-order serve loop gang-schedules small jobs concurrently
    and a shared-queue compile cache hands a second worker a zero-miss
    cold start.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax

from ramses_tpu.config import params_from_dict
from ramses_tpu.ensemble import queue as jq
from ramses_tpu.ensemble.batch import (EnsembleEngine, EnsembleSpec,
                                       build_member)
from ramses_tpu.ensemble.meshplan import MeshPlan
from ramses_tpu.ensemble.service import serve

pytestmark = pytest.mark.smoke

NDEV = min(8, len(jax.devices()))


def _hydro_params(nstepmax=6):
    """2D periodic Sedov-style base: nx=16 — slab-shardable over 8
    devices (2-cell shards == NGHOST) AND pack-shardable over any
    member count."""
    return params_from_dict({
        "run_params": {"hydro": True, "nstepmax": nstepmax},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "point"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "length_x": [10.0, 1.0], "length_y": [10.0, 1.0],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.0],
                        "p_region": [1e-5, 0.1]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.8,
                         "riemann": "hllc"},
        "output_params": {"tend": 1e9},
    }, ndim=2)


def _solo_windows(spec, k, windows):
    """Replay the engine's exact fused-window sequence on one member."""
    from ramses_tpu.grid.uniform import run_steps

    grid, state, tend, _ = build_member(spec, k, dtype=jnp.float64)
    u, t = state[0], jnp.asarray(0.0, jnp.float64)
    te = jnp.asarray(tend, jnp.float64)
    for n in windows:
        u, t, _ = run_steps(grid, u, t, te, n)
    return u, float(t)


# ---------------------------------------------------------------------
# bitwise parity across packings
# ---------------------------------------------------------------------
@pytest.mark.skipif(NDEV < 8, reason="needs 8 devices")
def test_packed_mesh_of_8_bitwise_vs_solo():
    """8 members packed over 8 per-device replicas == 8 solo runs,
    bitwise.  Members are data-parallel, so the GSPMD partition of the
    member axis must not change a single bit."""
    spec = EnsembleSpec(base=_hydro_params(nstepmax=6), nmember=8,
                        perturb_amp=0.01)
    eng = EnsembleEngine(spec, dtype=jnp.float64,
                         plan=MeshPlan.packed(tuple(range(8))))
    assert eng.groups[0].replicas == 8
    eng.run(chunk=4)
    assert eng.run_complete() and eng.nstep == 6
    info = eng.run_info()
    assert info["packing"]["mode"] == "packed"
    assert info["packing"]["group_replicas"] == [8]
    for k in range(8):
        solo_u, solo_t = _solo_windows(spec, k, (4, 2))
        ms = eng.member_state(k)
        assert np.asarray(ms["u"]).tobytes() == \
            np.asarray(solo_u).tobytes(), k
        assert ms["t"] == solo_t


@pytest.mark.skipif(NDEV < 8, reason="needs 8 devices")
def test_slab_member_bitwise_vs_standalone_sharded():
    """A slab-mode member == the standalone sharded sim through
    ``run_steps_halo`` on the same mesh, window for window."""
    from ramses_tpu.parallel import halo

    p = _hydro_params(nstepmax=6)
    spec = EnsembleSpec(base=p, nmember=1, perturb_amp=0.01)
    eng = EnsembleEngine(spec, dtype=jnp.float64,
                         plan=MeshPlan.slab(tuple(range(8))))
    eng.run(chunk=4)
    assert eng.run_complete() and eng.nstep == 6
    assert eng.run_info()["packing"]["mode"] == "slab"

    grid, state, tend, _ = build_member(spec, 0, dtype=jnp.float64)
    mesh = halo.make_halo_mesh(jax.devices()[:8])
    u, t = state[0], jnp.asarray(0.0, jnp.float64)
    for n in (4, 2):
        u, t, _ = halo.run_steps_halo(grid, mesh, u, t, float(tend), n)
    ms = eng.member_state(0)
    assert np.asarray(ms["u"]).tobytes() == np.asarray(u).tobytes()
    assert ms["t"] == float(t)


@pytest.mark.skipif(NDEV < 8, reason="needs 8 devices")
@pytest.mark.parametrize("first,second", [
    ("packed", "single"), ("single", "packed")])
def test_cross_packing_checkpoint_restore(tmp_path, first, second):
    """Save under one packing, restore under another, finish the run:
    bitwise identical to the uninterrupted solo windows."""
    plans = {"packed": MeshPlan.packed(tuple(range(8))),
             "single": MeshPlan.single()}
    spec = EnsembleSpec(base=_hydro_params(nstepmax=6), nmember=8,
                        perturb_amp=0.01)
    eng = EnsembleEngine(spec, dtype=jnp.float64, plan=plans[first])
    eng.run(chunk=4, nstepmax=4)          # first window only
    snap = eng.save(str(tmp_path))
    meta = json.load(open(os.path.join(snap, "ensemble.json")))
    assert meta["packing"]["mode"] == first

    eng2 = EnsembleEngine.from_checkpoint(spec, snap,
                                          dtype=jnp.float64,
                                          plan=plans[second])
    eng2.run(chunk=4)                     # remaining (2,) window
    assert eng2.run_complete() and eng2.nstep == 6
    for k in range(8):
        solo_u, solo_t = _solo_windows(spec, k, (4, 2))
        ms = eng2.member_state(k)
        assert np.asarray(ms["u"]).tobytes() == \
            np.asarray(solo_u).tobytes(), (first, second, k)
        assert ms["t"] == solo_t


# ---------------------------------------------------------------------
# one stacked fetch per chunk
# ---------------------------------------------------------------------
def test_multigroup_single_stacked_fetch_per_chunk(monkeypatch):
    """A static sweep that splits into TWO sub-batch groups still costs
    exactly ONE host round-trip per chunk: both groups' windows are
    dispatched async, then fetched in a single stacked device_get."""
    kw = dict(nmember=2, sweeps={"hydro.gamma": [1.4, 5.0 / 3.0]})
    # warm the compile caches so the counted run is pure dispatch
    EnsembleEngine(EnsembleSpec(base=_hydro_params(), **kw),
                   dtype=jnp.float64).run(chunk=4)
    eng = EnsembleEngine(EnsembleSpec(base=_hydro_params(), **kw),
                         dtype=jnp.float64)
    assert len(eng.groups) == 2
    calls = {"n": 0}
    real = jax.device_get

    def counted(x, _c=calls, _r=real):
        _c["n"] += 1
        return _r(x)

    with monkeypatch.context() as m:
        m.setattr(jax, "device_get", counted)
        eng.run(chunk=4)                  # windows (4, 2) -> 2 chunks
    assert eng.run_complete()
    assert calls["n"] == 2, calls


# ---------------------------------------------------------------------
# gang serve + shared compile cache
# ---------------------------------------------------------------------
_TINY_NML = """&RUN_PARAMS
hydro=.true.
nstepmax=2
/
&AMR_PARAMS
levelmin=2
levelmax=2
/
&OUTPUT_PARAMS
tend=1e9
/
&INIT_PARAMS
d_region=1.0
p_region=1e-5
/
&ENSEMBLE_PARAMS
nmember=2
perturb_amp=1e-3
perturb_seed=7
chunk_steps=2
/
"""


class _CapTel:
    closed = False

    def __init__(self):
        self.events = []

    def record_event(self, kind, **kw):
        self.events.append((kind, kw))

    def close(self, *a, **k):
        pass


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
def test_gang_serve_overlaps_small_jobs(tmp_path):
    """Three packable small jobs gang onto disjoint submeshes in ONE
    claim round; every result records its packing and the gang's
    busy-device fraction."""
    qd = str(tmp_path / "q")
    for i in range(3):
        jq.submit(qd, _TINY_NML, job_id=f"small{i}")
    tel = _CapTel()
    serve(qd, idle_exit=True, max_attempts=1, telemetry=tel,
          log=lambda *a, **k: None)
    done = sorted(os.listdir(os.path.join(qd, "done")))
    assert done == [f"small{i}.json" for i in range(3)]
    gangs = [kw for kind, kw in tel.events if kind == "gang_schedule"]
    assert gangs and max(len(g["job_ids"]) for g in gangs) > 1
    for name in done:
        rec = json.load(open(os.path.join(qd, "done", name)))
        res = rec["result"]
        assert res["packing"]["mode"] in ("packed", "single")
        assert res["gang"]["jobs"] > 1
        assert 0.0 < res["gang"]["busy_frac"] <= 1.0
        assert res["queue_wait_s"] >= 0.0
        assert res["scenarios_per_device_s"] > 0.0


@pytest.mark.slow
def test_second_worker_zero_miss_cold_start(tmp_path):
    """The queue's shared persistent compile cache: worker 1 compiles a
    config cold, worker 2 (a fresh process) serves the SAME config with
    zero compile-cache misses."""
    qd = str(tmp_path / "q")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    env.pop("RAMSES_COMPILE_CACHE", None)
    code = ("import sys; from ramses_tpu.ensemble.service import serve;"
            "serve(sys.argv[1], idle_exit=True, max_jobs=1,"
            "      max_attempts=1)")
    # sequential submits: each fresh worker process serves exactly one
    # job, so the second worker's cache stats are a true cold start
    for jid in ("first", "second"):
        jq.submit(qd, _TINY_NML, job_id=jid)
        subprocess.run([sys.executable, "-c", code, qd], env=env,
                       check=True, timeout=300)
    assert os.path.isdir(os.path.join(qd, "compile_cache"))
    recs = {name.split(".")[0]: json.load(
        open(os.path.join(qd, "done", name)))
        for name in os.listdir(os.path.join(qd, "done"))}
    assert set(recs) == {"first", "second"}
    first, second = recs["first"]["result"], recs["second"]["result"]
    assert first["compile_cache_misses"] > 0       # cold queue
    assert second["compile_cache_misses"] == 0, second
    assert second["compile_cache_hits"] > 0
