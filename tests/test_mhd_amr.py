"""AMR MHD: CT on the hierarchy with div-free transfer operators.

Oracles follow the reference's MHD test strategy (``tests/mhd/``): the
uniform CT solver is the trusted baseline (itself validated against
Brio-Wu / Orszag-Tang in test_mhd.py); the AMR solver must (a) reduce
to it on a complete level, (b) beat the coarse uniform solution on a
shock tube, (c) keep the staggered divergence at machine zero through
regrids (``mhd/interpol_hydro.f90`` interpol_mag invariant), and
(d) conserve mass/energy across coarse-fine interfaces.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.config import load_params
from ramses_tpu.mhd import core as mcore
from ramses_tpu.mhd.amr import MhdAmrSim
from ramses_tpu.mhd.core import IBX, IP, NCOMP
from ramses_tpu.mhd.driver import MhdSimulation

NML = "namelists/tube_mhd.nml"


def _tube_params(lmin, lmax, ndim=1):
    p = load_params(NML, ndim=ndim)
    p.amr.levelmin, p.amr.levelmax = lmin, lmax
    return p


def test_amr_matches_uniform_on_complete_level():
    """levelmin == levelmax: the AMR driver's dense path must reproduce
    the uniform CT stepper step for step."""
    p = _tube_params(6, 6)
    amr = MhdAmrSim(p, dtype=jnp.float64)
    uni = MhdSimulation(p, dtype=jnp.float64)
    for _ in range(4):
        amr.step_coarse(amr.coarse_dt())
    uni.evolve(tend=amr.t + 1e-30, nstepmax=4)
    assert uni.nstep == 4
    assert uni.t == pytest.approx(amr.t, rel=1e-12)
    m = amr.maps[6]
    rows = np.asarray(amr.u[6])[:m.noct * 2]
    dense = rows[np.argsort(np.asarray(m.perm))]  # not needed: use perm
    dense = rows[m.inv_perm]
    got = dense.T                                    # [nvar, n]
    want = np.asarray(uni.u).reshape(uni.cfg.nvar, -1)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)


@pytest.mark.slow
def test_briowu_amr_beats_coarse_uniform():
    """AMR (lmin=5, lmax=7) L1 error vs the 2^7 uniform run must be
    well below the 2^5 uniform run's — refinement is doing its job."""
    tend = 0.12
    fine = MhdSimulation(_tube_params(7, 7), dtype=jnp.float64)
    fine.evolve(tend=tend)
    coarse = MhdSimulation(_tube_params(5, 5), dtype=jnp.float64)
    coarse.evolve(tend=tend)

    p = _tube_params(5, 7)
    p.refine.err_grad_d = 0.02
    p.refine.err_grad_p = 0.05
    amr = MhdAmrSim(p, dtype=jnp.float64)
    amr.evolve(tend)

    rho_f = np.asarray(fine.u[0])                    # [128]
    x_f = (np.arange(128) + 0.5) * fine.dx

    def l1(x, rho, w):
        ref = np.interp(x, x_f, rho_f)
        return np.sum(np.abs(rho - ref) * w)

    # AMR leaves
    err_amr = 0.0
    for l in amr.levels():
        c, u = amr.leaf_sample(l)
        err_amr += l1(c[:, 0], u[:, 0], amr.dx(l))
    x_c = (np.arange(32) + 0.5) * coarse.dx
    err_coarse = l1(x_c, np.asarray(coarse.u[0]), coarse.dx)
    assert err_amr < 0.5 * err_coarse
    # the refined tree actually refined around the waves
    assert amr.tree.noct(7) > 0


def _make_ot(lmin, lmax, n_warm_flags=2):
    """Orszag-Tang vortex on the hierarchy, faces from the vector
    potential A_z so divB = 0 to round-off at every level and the
    coarse face is EXACTLY the mean of its fine faces."""
    p = load_params(NML, ndim=2)
    p.amr.levelmin, p.amr.levelmax = lmin, lmax
    p.amr.boxlen = 1.0
    p.boundary.nboundary = 0          # fully periodic
    p.refine.err_grad_d = 0.05
    p.refine.err_grad_p = 0.1
    p.refine.err_grad_b = 0.1
    sim = MhdAmrSim(p, dtype=jnp.float64)

    g = 5.0 / 3.0
    rho0 = 25.0 / (36.0 * np.pi)
    p0 = 5.0 / (12.0 * np.pi)
    b0 = 1.0 / np.sqrt(4.0 * np.pi)
    two_pi = 2.0 * np.pi

    def az(x, y):
        return b0 * (np.cos(4.0 * np.pi * x) / (4.0 * np.pi)
                     + np.cos(two_pi * y) / two_pi)

    def set_state(sim):
        for l in sim.levels():
            m = sim.maps[l]
            dxl = sim.dx(l)
            cc = sim.tree.cell_coords(l).astype(np.float64)
            x0, y0 = cc[:, 0] * dxl, cc[:, 1] * dxl
            n = len(cc)
            bf = np.zeros((m.ncell_pad, NCOMP, 2))
            # Bx = dAz/dy on x-faces; By = -dAz/dx on y-faces
            bf[:n, 0, 0] = (az(x0, y0 + dxl) - az(x0, y0)) / dxl
            bf[:n, 0, 1] = (az(x0 + dxl, y0 + dxl)
                            - az(x0 + dxl, y0)) / dxl
            bf[:n, 1, 0] = -(az(x0 + dxl, y0) - az(x0, y0)) / dxl
            bf[:n, 1, 1] = -(az(x0 + dxl, y0 + dxl)
                             - az(x0, y0 + dxl)) / dxl
            xc, yc = x0 + 0.5 * dxl, y0 + 0.5 * dxl
            q = np.zeros((sim.mcfg.nvar, m.ncell_pad))
            q[0] = sim.mcfg.smallr
            q[0, :n] = rho0
            q[1, :n] = -np.sin(two_pi * yc)
            q[2, :n] = np.sin(two_pi * xc)
            q[IP] = 1e-20
            q[IP, :n] = p0
            for c in range(NCOMP):
                q[IBX + c] = 0.5 * (bf[:, c, 0] + bf[:, c, 1])
            u = np.asarray(mcore.prim_to_cons(jnp.asarray(q), sim.mcfg)).T
            sim.u[l] = jnp.asarray(u)
            sim.bfs[l] = jnp.asarray(bf)
        sim._restrict_all()
        sim._dt_cache = None

    set_state(sim)
    # let the initial tree adapt to the actual state
    for _ in range(n_warm_flags):
        sim.regrid()
        set_state(sim)
    return sim


@pytest.mark.slow
def test_ot_divb_machine_zero_across_regrids():
    sim = _make_ot(4, 6)
    assert sim.max_divb() < 1e-12
    for _ in range(6):
        sim.regrid()
        sim.step_coarse(sim.coarse_dt())
    assert sim.tree.noct(5) > 0       # refinement actually active
    assert sim.max_divb() < 1e-11


@pytest.mark.slow
def test_ot_amr_conservation():
    """Mass/energy conserved across coarse-fine interfaces (masked
    fluxes + fine corrections, the hydro scheme applied to MHD)."""
    sim = _make_ot(4, 5)
    tot0 = sim.totals()
    for _ in range(5):
        sim.regrid()
        sim.step_coarse(sim.coarse_dt())
    tot1 = sim.totals()
    assert tot1[0] == pytest.approx(tot0[0], rel=1e-12)       # mass
    assert tot1[IP] == pytest.approx(tot0[IP], rel=1e-9)      # energy


@pytest.mark.slow          # ~19s; nightly tier on the 1-core box
def test_mhd_amr_snapshot_roundtrip(tmp_path):
    """Dump → restore: cell state AND duplicated staggered faces come
    back exactly, divB stays machine-zero, and continued stepping
    matches the uncheckpointed run."""
    from ramses_tpu.mhd.amr import MhdAmrSim as Sim

    sim = _make_ot(4, 5)
    for _ in range(3):
        sim.regrid()
        sim.step_coarse(sim.coarse_dt())
    assert sim.tree.noct(5) > 0

    outdir = sim.dump(1, str(tmp_path))
    p = load_params(NML, ndim=2)
    p.amr.levelmin, p.amr.levelmax = 4, 5
    p.amr.boxlen = 1.0
    p.boundary.nboundary = 0
    p.refine.err_grad_d = 0.05
    p.refine.err_grad_p = 0.1
    p.refine.err_grad_b = 0.1
    sim2 = Sim.from_snapshot(p, outdir, dtype=jnp.float64)

    assert sim2.t == pytest.approx(sim.t, rel=1e-14)
    for l in sim.levels():
        nc = sim.maps[l].noct * 4
        np.testing.assert_allclose(
            np.asarray(sim2.u[l])[:nc], np.asarray(sim.u[l])[:nc],
            rtol=1e-12, atol=1e-14)
        np.testing.assert_allclose(
            np.asarray(sim2.bfs[l])[:nc], np.asarray(sim.bfs[l])[:nc],
            rtol=1e-12, atol=1e-14)
    assert sim2.max_divb() < 1e-11

    # continued stepping agrees (same dt sequence from the same state)
    for s in (sim, sim2):
        s.step_coarse(s.coarse_dt())
    for l in sim.levels():
        nc = sim.maps[l].noct * 4
        np.testing.assert_allclose(
            np.asarray(sim2.u[l])[:nc], np.asarray(sim.u[l])[:nc],
            rtol=1e-10, atol=1e-12)


@pytest.mark.slow
def test_mhd_amr_self_gravity_collapse():
    """poisson=.true. on the MHD hierarchy: a dense magnetised blob
    develops inward radial momentum under its own gravity while divB
    stays machine-zero and mass is conserved (the gravity kicks ride
    the CT step at every level substep)."""
    p = load_params(NML, ndim=2)
    p.amr.levelmin, p.amr.levelmax = 4, 5
    p.amr.boxlen = 1.0
    p.boundary.nboundary = 0
    p.refine.err_grad_d = 0.2
    p.run.poisson = True
    p.init.nregion = 2
    p.init.region_type = ["square", "square"]
    p.init.x_center = [0.5, 0.5]
    p.init.y_center = [0.5, 0.5]
    p.init.length_x = [10.0, 0.25]
    p.init.length_y = [10.0, 0.25]
    p.init.exp_region = [10.0, 2.0]
    p.init.d_region = [0.1, 50.0]
    p.init.p_region = [0.05, 0.05]
    p.init.A_region = [0.1, 0.1]           # uniform Bx threads the box
    p.init.B_region = [0.0, 0.0]
    p.init.C_region = [0.0, 0.0]
    # runs tube_mhd.nml's riemann='roe' + the default llf corner solver
    sim = MhdAmrSim(p, dtype=jnp.float64)
    assert sim.gravity
    m0 = sim.totals()[0]

    def rho_max():
        return max(float(np.asarray(sim.u[l])[:sim.maps[l].noct * 4,
                                              0].max())
                   for l in sim.levels())

    # the force field points at the blob
    sim.solve_gravity()
    l = sim.lmin
    xc = sim.tree.cell_centers(l, sim.boxlen)
    rel = xc - 0.5
    rr = np.sqrt((rel ** 2).sum(1))
    sel = (rr > 0.12) & (rr < 0.3)
    fg = np.asarray(sim.fg[l])[:len(xc)]
    assert (fg[sel] * rel[sel] / rr[sel, None]).sum() < 0.0

    r0 = rho_max()
    for _ in range(4):
        sim.regrid()
        sim.step_coarse(sim.coarse_dt())
    assert sim.max_divb() < 1e-11
    assert np.isclose(sim.totals()[0], m0, rtol=1e-11)
    # self-gravitating collapse: the blob's peak density grows
    assert rho_max() > 1.3 * r0


# ----------------------------------------------------------------------
# particles on the MHD hierarchy
# ----------------------------------------------------------------------
def _pm_pset(n, ndim, seed=0, vmax=0.05):
    from ramses_tpu.pm.particles import ParticleSet
    rng = np.random.default_rng(seed)
    return ParticleSet.make(
        rng.uniform(0.05, 0.95, (n, ndim)),
        rng.uniform(-vmax, vmax, (n, ndim)),
        np.full(n, 1.0 / n))


def _pm_params(extra_init, ndim=2):
    from ramses_tpu.config import params_from_string
    txt = "\n".join([
        "&RUN_PARAMS", "poisson=.true.", "pic=.true.", "/",
        "&AMR_PARAMS", "levelmin=4", "levelmax=5", "boxlen=1.0", "/",
        "&HYDRO_PARAMS", "courant_factor=0.5", "/",
        "&REFINE_PARAMS", "x_refine=0,0,0,0.5", "y_refine=0,0,0,0.5",
        "r_refine=-1,-1,-1,0.2", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0"] + extra_init + ["/"])
    return params_from_string(txt, ndim=ndim)


@pytest.mark.slow
def test_mhd_amr_particles_match_hydro_amr():
    """With a vanishing field and uniform gas the MHD hierarchy's PM
    layer must reproduce the hydro hierarchy's particle trajectories:
    same CIC deposits, same per-level Poisson solve, same KDK order
    (``synchro_fine``/``move_fine`` called identically from the MHD and
    hydro ``amr_step`` in the reference)."""
    import jax

    from ramses_tpu.amr.hierarchy import AmrSim

    ndim = 2
    ps = _pm_pset(40, ndim, seed=7)
    simm = MhdAmrSim(_pm_params(["A_region=1e-12"], ndim),
                     dtype=jnp.float64, particles=jax.device_put(ps))
    simh = AmrSim(_pm_params([], ndim), dtype=jnp.float64,
                  particles=jax.device_put(ps))
    assert simm.pic and simh.pic
    dt = 2e-3
    for _ in range(4):
        simm.step_coarse(dt)
        simh.step_coarse(dt)
    np.testing.assert_allclose(np.asarray(simm.p.x),
                               np.asarray(simh.p.x), atol=1e-6)
    np.testing.assert_allclose(np.asarray(simm.p.v),
                               np.asarray(simh.p.v), atol=1e-5)
    assert simm.max_divb() < 1e-11


@pytest.mark.slow          # ~13s; nightly tier on the 1-core box
def test_mhd_amr_particles_feel_blob_and_dt_caps():
    """Particles around a magnetised self-gravitating blob fall toward
    it, the particle/free-fall dt caps enter coarse_dt, and divB stays
    machine-zero with the PM layer active."""
    import jax

    p = _pm_params(["A_region=0.05"], ndim=2)
    p.init.nregion = 2
    p.init.region_type = ["square", "square"]
    p.init.x_center = [0.5, 0.5]
    p.init.y_center = [0.5, 0.5]
    p.init.length_x = [10.0, 0.25]
    p.init.length_y = [10.0, 0.25]
    p.init.exp_region = [10.0, 2.0]
    p.init.d_region = [0.1, 50.0]
    p.init.p_region = [0.05, 0.05]
    p.init.u_region = [0.0, 0.0]
    p.init.v_region = [0.0, 0.0]
    p.init.w_region = [0.0, 0.0]
    p.init.A_region = [0.05, 0.05]
    p.init.B_region = [0.0, 0.0]
    p.init.C_region = [0.0, 0.0]
    # a ring of test particles at radius 0.3
    th = np.linspace(0.0, 2 * np.pi, 12, endpoint=False)
    from ramses_tpu.pm.particles import ParticleSet
    ps = ParticleSet.make(
        np.stack([0.5 + 0.3 * np.cos(th), 0.5 + 0.3 * np.sin(th)], 1),
        np.zeros((12, 2)), np.full(12, 1e-6))
    sim = MhdAmrSim(p, dtype=jnp.float64, particles=jax.device_put(ps))
    assert sim.pic and sim.gravity
    for _ in range(3):
        sim.regrid()
        sim.step_coarse(sim.coarse_dt())
    # free-fall / particle caps are live once _rho_max exists
    assert sim._rho_max is not None and len(sim._aux_dts()) >= 2
    # net inward radial velocity
    rel = np.asarray(sim.p.x) - 0.5
    vr = (np.asarray(sim.p.v) * rel).sum(1) / np.sqrt((rel ** 2).sum(1))
    assert vr.mean() < 0.0
    assert sim.max_divb() < 1e-11


def test_mhd_amr_particle_restart(tmp_path):
    """Snapshot + restart round-trips the particle set through the MHD
    AMR path (``pm/output_part.f90`` companion of the MHD dump)."""
    import jax

    p = _pm_params(["A_region=0.02"], ndim=2)
    ps = _pm_pset(24, 2, seed=11)
    sim = MhdAmrSim(p, dtype=jnp.float64, particles=jax.device_put(ps))
    for _ in range(2):
        sim.step_coarse(sim.coarse_dt())
    out = sim.dump(1, str(tmp_path))
    sim2 = MhdAmrSim.from_snapshot(p, out, dtype=jnp.float64)
    assert sim2.pic and sim2.p is not None
    o1 = np.argsort(np.asarray(sim.idp_active()) if hasattr(sim, "idp_active")
                    else np.asarray(sim.p.idp))
    o2 = np.argsort(np.asarray(sim2.p.idp))
    np.testing.assert_allclose(np.asarray(sim.p.x)[o1],
                               np.asarray(sim2.p.x)[o2], rtol=1e-12)
    np.testing.assert_allclose(np.asarray(sim.p.v)[o1],
                               np.asarray(sim2.p.v)[o2], rtol=1e-12)
    # restart == continuous run: dt_old round-trips through the dump
    # (the pending closing half-kick is 0.5*(dt_old + dt)), so one more
    # step from each must agree to snapshot-conversion roundoff
    assert sim2.dt_old == pytest.approx(sim.dt_old, rel=1e-12)
    sim.step_coarse(sim.coarse_dt())
    sim2.step_coarse(sim2.coarse_dt())
    # tolerance: the partial-level PCG re-converges from a cold start
    # after the restart, so forces differ by the epsilon-bounded solver
    # noise (~3e-4 force -> ~3e-6 velocity at this dt); a missing
    # closing half-kick or a dt mismatch shows up at ~1e-3
    np.testing.assert_allclose(np.asarray(sim.p.x)[o1],
                               np.asarray(sim2.p.x)[o2], atol=1e-7)
    np.testing.assert_allclose(np.asarray(sim.p.v)[o1],
                               np.asarray(sim2.p.v)[o2], atol=1e-5)


def test_mhd_amr_tracers():
    """tracer=.true. on the MHD hierarchy: the velocity-tracer layer
    reads the shared [rho, mom...] columns, so tracers advect with the
    MHD gas (``pm/move_tracer.f90`` under SOLVER=mhd)."""
    p = _tube_params(5, 6)
    p.boundary.nboundary = 0            # periodic: population conserved
    p.run.tracer = True
    p.run.tracer_per_cell = 0.5
    p.refine.err_grad_d = 0.05
    sim = MhdAmrSim(p, dtype=jnp.float64)
    assert sim.tracer_x is not None and len(sim.tracer_x) > 0
    x0 = sim.tracer_x.copy()
    sim.evolve(0.08)
    moved = np.abs(np.asarray(sim.tracer_x) - x0)
    assert moved.max() > 1e-4 and np.isfinite(sim.tracer_x).all()
