"""User patch (plug-in overlay) mechanism.

The reference's entire extensibility story is compile-time file
shadowing: ``make PATCH=../mypatch`` prepends the patch directory to
VPATH so a user-provided ``condinit.f90``/``gravana.f90``/
``boundana.f90``/extra ``amr_step`` physics replaces the stock one
(``bin/Makefile:153-160``; ``patch/`` tree ships dozens of examples).

The runtime equivalent here: a plain Python file named in the namelist
(``&RUN_PARAMS patch='mypatch.py'``) or on the CLI (``--patch``),
imported at startup.  Any function it defines whose name matches a
known hook overrides the stock implementation:

  ``condinit(x, dx, params, cfg) -> q [nvar, ...]``
      primitive ICs at the given cell-centre coordinate arrays —
      replaces the &INIT_PARAMS region machinery
      (``hydro/condinit.f90``).  ``x`` is a list of ndim coordinate
      arrays (uniform grids pass meshgrids, the AMR driver flat
      per-level centre lists): write it shape-generically.  ``dx`` may
      be None (the rhd paths evaluate on arbitrary centre lists).  The
      hydro and SRHD solvers consult it; MHD warns and keeps regions
      (its ICs need divergence-free staggered faces).
  ``gravana(x, gravity_type, gravity_params, boxlen) -> g [ndim, ...]``
      analytic gravity field (``poisson/gravana.f90``); consulted for
      every ``gravity_type > 0``.
  ``boundana(d, side, cfg[, x]) -> primitive values (rho, v..., P)``
      imposed-inflow state for face (dimension, side) — replaces the
      &BOUNDARY_PARAMS d/u/v/w/p_bound constants with computed ones
      (``hydro/boundana.f90``).  Declaring an ``x`` keyword makes the
      hook POSITION-DEPENDENT: it receives the ghost block's
      cell-centre coordinate arrays (one per dim) and may return
      per-cell primitive arrays (``boundana.f90:45`` per-cell states).
  ``source(sim, dt) -> None``
      arbitrary extra physics at coarse-step cadence, mutating the
      simulation in place — the runtime analogue of patching extra
      calls into ``amr_step`` (both the uniform ``Simulation`` and
      ``AmrSim`` call it after their stock source passes).

Hooks are optional and independent; unknown names are ignored (a patch
may carry helpers).  ``install(None)`` / ``clear()`` resets to stock
behaviour (tests use this).

Hooks that run inside jitted kernels (``gravana``, ``boundana``) are
bound at TRACE time; installing/clearing a patch whose trace-time
hooks differ therefore drops JAX's compilation caches so the next
simulation re-traces with the new behaviour (a same-shape second sim
would otherwise silently reuse the previous patch's compiled kernels).
Swapping patches while a simulation object is mid-run remains
unsupported.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Callable, Dict, Optional

HOOK_NAMES = ("condinit", "gravana", "boundana", "source")
# hooks whose lookup happens at jit TRACE time: swapping them must
# drop compiled kernels or a same-shape second sim silently reuses the
# previous patch's traced behaviour
_TRACED_HOOKS = ("gravana", "boundana")


def _drop_jit_caches_if_needed(before: dict):
    """Clear JAX's compilation caches when the set/identity of
    trace-time hooks changed (install/clear between simulations)."""
    changed = any(before.get(h) is not _active.get(h)
                  for h in _TRACED_HOOKS)
    if changed:
        try:
            import jax
            jax.clear_caches()
        except Exception:
            pass

_active: Dict[str, Callable] = {}
_module = None
_source: Optional[str] = None      # file path when loaded from disk
_auto = False                      # True: installed from a namelist


def install(path_or_module, verbose: bool = False, _from_params=False):
    """Load a patch file (or accept a ready module) and register its
    hooks.  Replaces any previously installed patch."""
    global _module, _source, _auto
    before = dict(_active)
    _clear_state()
    if not path_or_module:
        _drop_jit_caches_if_needed(before)
        return None
    if isinstance(path_or_module, str):
        path = path_or_module
        if not os.path.exists(path):
            raise FileNotFoundError(f"patch file not found: {path}")
        name = "ramses_tpu_patch_" + \
            os.path.splitext(os.path.basename(path))[0]
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _source = os.path.abspath(path)
    else:
        mod = path_or_module
    _module = mod
    _auto = _from_params
    found = []
    for h in HOOK_NAMES:
        fn = getattr(mod, h, None)
        if callable(fn):
            _active[h] = fn
            found.append(h)
    if verbose:
        print(f"patch: {getattr(mod, '__name__', mod)} overrides "
              f"{found or 'nothing'}")
    _drop_jit_caches_if_needed(before)
    return mod


def _clear_state():
    global _module, _source, _auto
    _active.clear()
    _module = None
    _source = None
    _auto = False


def clear():
    before = dict(_active)
    _clear_state()
    _drop_jit_caches_if_needed(before)


def hook(name: str) -> Optional[Callable]:
    """The installed override for ``name``, or None (stock behaviour)."""
    return _active.get(name)


def maybe_install_from_params(params, verbose: bool = False):
    """Reconcile the active patch with the namelist's ``&RUN_PARAMS
    patch=``; drivers call this on construction.

    Explicit :func:`install` calls (CLI ``--patch``, tests) win over
    the namelist.  A namelist-auto-installed patch is swapped out when
    a later simulation names a different file, and cleared when a later
    simulation names none — a second sim in the same process must not
    silently inherit the first one's hooks."""
    path = str(getattr(params.run, "patch", "") or "").strip("'\" ")
    if _module is not None and not _auto:
        return                     # explicit install wins
    if not path:
        if _auto:
            clear()
        return
    if _source != os.path.abspath(path):
        install(path, verbose=verbose, _from_params=True)
