"""1D MHD Riemann solvers on rotated interface states.

Counterpart of the reference's per-direction solvers dispatched from
``mag_unsplit`` (``mhd/umuscl.f90:1393``; options llf|hll|hlld,
``hydro/read_hydro_params.f90:184-223``).  HLLD follows Miyoshi & Kusano
(2005), branchless with ``jnp.where`` region selection so the whole face
batch resolves in one fused XLA program.

Interface layout (normal first): [ρ, v_n, v_t1, v_t2, P, B_n, B_t1, B_t2,
passives…].  The normal field ``B_n`` is the staggered face value, shared
by both sides (slot 5 of ql/qr is ignored; ``bn`` is passed separately).
Returned flux layout matches; the B_n flux slot is zero.
"""

from __future__ import annotations

import jax.numpy as jnp

from ramses_tpu.mhd.core import MhdStatic

_EPS = 1e-30


def _split(q, bn):
    return (q[0], q[1], q[2], q[3], q[4], bn, q[6], q[7])


def _cons(r, vn, vt1, vt2, p, bn, bt1, bt2, gamma):
    e = (p / (gamma - 1.0) + 0.5 * r * (vn ** 2 + vt1 ** 2 + vt2 ** 2)
         + 0.5 * (bn ** 2 + bt1 ** 2 + bt2 ** 2))
    return [r, r * vn, r * vt1, r * vt2, e, bn, bt1, bt2]


def _flux(r, vn, vt1, vt2, p, bn, bt1, bt2, gamma):
    b2 = bn ** 2 + bt1 ** 2 + bt2 ** 2
    ptot = p + 0.5 * b2
    vdotb = vn * bn + vt1 * bt1 + vt2 * bt2
    e = (p / (gamma - 1.0) + 0.5 * r * (vn ** 2 + vt1 ** 2 + vt2 ** 2)
         + 0.5 * b2)
    return [r * vn,
            r * vn * vn - bn * bn + ptot,
            r * vn * vt1 - bn * bt1,
            r * vn * vt2 - bn * bt2,
            (e + ptot) * vn - bn * vdotb,
            jnp.zeros_like(r),
            vn * bt1 - vt1 * bn,
            vn * bt2 - vt2 * bn]


def _fast(r, p, bn, bt1, bt2, gamma, smallc):
    c2 = gamma * p / r
    b2 = (bn ** 2 + bt1 ** 2 + bt2 ** 2) / r
    s = c2 + b2
    disc = jnp.sqrt(jnp.maximum(s * s - 4.0 * c2 * bn ** 2 / r, 0.0))
    return jnp.sqrt(jnp.maximum(0.5 * (s + disc), smallc ** 2))


def _sanitize(q, cfg):
    r = jnp.maximum(q[0], cfg.smallr)
    p = jnp.maximum(q[4], cfg.smallr * cfg.smallc ** 2)
    return r, p


def solve(ql, qr, bn, cfg: MhdStatic):
    if cfg.riemann == "llf":
        f = llf(ql, qr, bn, cfg)
    elif cfg.riemann == "hll":
        f = hll(ql, qr, bn, cfg)
    elif cfg.riemann == "hlld":
        f = hlld(ql, qr, bn, cfg)
    elif cfg.riemann == "roe":
        from ramses_tpu.mhd import roe as roemod
        f = roemod.roe(ql, qr, bn, cfg)
    elif cfg.riemann == "upwind":
        from ramses_tpu.mhd import roe as roemod
        f = roemod.upwind(ql, qr, bn, cfg)
    else:
        raise NotImplementedError(f"mhd riemann={cfg.riemann}")
    if cfg.npassive:
        mass = f[0]
        pf = [jnp.where(mass > 0.0, mass * ql[8 + s], mass * qr[8 + s])
              for s in range(cfg.npassive)]
        f = jnp.concatenate([f, jnp.stack(pf)], axis=0)
    return f


def llf(ql, qr, bn, cfg: MhdStatic):
    g = cfg.gamma
    rl, pl = _sanitize(ql, cfg)
    rr, pr = _sanitize(qr, cfg)
    sl = _split(ql, bn)
    sr = _split(qr, bn)
    al = _fast(rl, pl, bn, ql[6], ql[7], g, cfg.smallc) + jnp.abs(ql[1])
    ar = _fast(rr, pr, bn, qr[6], qr[7], g, cfg.smallc) + jnp.abs(qr[1])
    a = jnp.maximum(al, ar)
    fl = _flux(rl, *sl[1:5], bn, *sl[6:], g)
    fr = _flux(rr, *sr[1:5], bn, *sr[6:], g)
    ul = _cons(rl, *sl[1:5], bn, *sl[6:], g)
    ur = _cons(rr, *sr[1:5], bn, *sr[6:], g)
    return jnp.stack([0.5 * (a1 + a2) - 0.5 * a * (u2 - u1)
                      for a1, a2, u1, u2 in zip(fl, fr, ul, ur)])


def _wave_bounds(ql, qr, bn, cfg):
    g = cfg.gamma
    rl, pl = _sanitize(ql, cfg)
    rr, pr = _sanitize(qr, cfg)
    cl = _fast(rl, pl, bn, ql[6], ql[7], g, cfg.smallc)
    cr = _fast(rr, pr, bn, qr[6], qr[7], g, cfg.smallc)
    sl_speed = jnp.minimum(ql[1] - cl, qr[1] - cr)
    sr_speed = jnp.maximum(ql[1] + cl, qr[1] + cr)
    return rl, pl, rr, pr, sl_speed, sr_speed


def hll(ql, qr, bn, cfg: MhdStatic):
    g = cfg.gamma
    rl, pl, rr, pr, SL, SR = _wave_bounds(ql, qr, bn, cfg)
    fl = _flux(rl, ql[1], ql[2], ql[3], pl, bn, ql[6], ql[7], g)
    fr = _flux(rr, qr[1], qr[2], qr[3], pr, bn, qr[6], qr[7], g)
    ul = _cons(rl, ql[1], ql[2], ql[3], pl, bn, ql[6], ql[7], g)
    ur = _cons(rr, qr[1], qr[2], qr[3], pr, bn, qr[6], qr[7], g)
    SLc = jnp.minimum(SL, 0.0)
    SRc = jnp.maximum(SR, 0.0)
    den = SRc - SLc + _EPS
    return jnp.stack([
        (SRc * f1 - SLc * f2 + SLc * SRc * (u2 - u1)) / den
        for f1, f2, u1, u2 in zip(fl, fr, ul, ur)])


def hlld(ql, qr, bn, cfg: MhdStatic):
    """Miyoshi & Kusano (2005) five-wave solver, fully vectorized."""
    g = cfg.gamma
    rl, pl, rr, pr, SL, SR = _wave_bounds(ql, qr, bn, cfg)
    vnl, vt1l, vt2l, bt1l, bt2l = ql[1], ql[2], ql[3], ql[6], ql[7]
    vnr, vt1r, vt2r, bt1r, bt2r = qr[1], qr[2], qr[3], qr[6], qr[7]
    b2l = bn ** 2 + bt1l ** 2 + bt2l ** 2
    b2r = bn ** 2 + bt1r ** 2 + bt2r ** 2
    ptl = pl + 0.5 * b2l
    ptr = pr + 0.5 * b2r

    dl = rl * (SL - vnl)
    dr = rr * (SR - vnr)
    SM = (dr * vnr - dl * vnl - ptr + ptl) / (dr - dl + _EPS)
    pts = (dr * ptl - dl * ptr + dl * dr * (vnr - vnl)) / (dr - dl + _EPS)

    # star states
    rsl = dl / (SL - SM + _EPS)
    rsr = dr / (SR - SM + _EPS)
    denl = dl * (SL - SM) - bn ** 2
    denr = dr * (SR - SM) - bn ** 2
    degl = jnp.abs(denl) < 1e-12 * (rl * (jnp.abs(SL) + jnp.abs(vnl)) ** 2
                                    + bn ** 2 + _EPS)
    degr = jnp.abs(denr) < 1e-12 * (rr * (jnp.abs(SR) + jnp.abs(vnr)) ** 2
                                    + bn ** 2 + _EPS)
    safe_denl = jnp.where(degl, 1.0, denl)
    safe_denr = jnp.where(degr, 1.0, denr)
    vt1sl = jnp.where(degl, vt1l,
                      vt1l - bn * bt1l * (SM - vnl) / safe_denl)
    vt2sl = jnp.where(degl, vt2l,
                      vt2l - bn * bt2l * (SM - vnl) / safe_denl)
    bt1sl = jnp.where(degl, bt1l,
                      bt1l * (dl * (SL - vnl) - bn ** 2) / safe_denl)
    bt2sl = jnp.where(degl, bt2l,
                      bt2l * (dl * (SL - vnl) - bn ** 2) / safe_denl)
    vt1sr = jnp.where(degr, vt1r,
                      vt1r - bn * bt1r * (SM - vnr) / safe_denr)
    vt2sr = jnp.where(degr, vt2r,
                      vt2r - bn * bt2r * (SM - vnr) / safe_denr)
    bt1sr = jnp.where(degr, bt1r,
                      bt1r * (dr * (SR - vnr) - bn ** 2) / safe_denr)
    bt2sr = jnp.where(degr, bt2r,
                      bt2r * (dr * (SR - vnr) - bn ** 2) / safe_denr)

    el = (pl / (g - 1.0) + 0.5 * rl * (vnl ** 2 + vt1l ** 2 + vt2l ** 2)
          + 0.5 * b2l)
    er = (pr / (g - 1.0) + 0.5 * rr * (vnr ** 2 + vt1r ** 2 + vt2r ** 2)
          + 0.5 * b2r)
    vbl = vnl * bn + vt1l * bt1l + vt2l * bt2l
    vbsl = SM * bn + vt1sl * bt1sl + vt2sl * bt2sl
    vbr = vnr * bn + vt1r * bt1r + vt2r * bt2r
    vbsr = SM * bn + vt1sr * bt1sr + vt2sr * bt2sr
    esl = ((SL - vnl) * el - ptl * vnl + pts * SM + bn * (vbl - vbsl)) \
        / (SL - SM + _EPS)
    esr = ((SR - vnr) * er - ptr * vnr + pts * SM + bn * (vbr - vbsr)) \
        / (SR - SM + _EPS)

    # Alfvén (double-star) states
    sq_rsl = jnp.sqrt(jnp.maximum(rsl, cfg.smallr))
    sq_rsr = jnp.sqrt(jnp.maximum(rsr, cfg.smallr))
    SLs = SM - jnp.abs(bn) / sq_rsl
    SRs = SM + jnp.abs(bn) / sq_rsr
    sgn = jnp.sign(bn)
    ssum = sq_rsl + sq_rsr + _EPS
    vt1ss = (sq_rsl * vt1sl + sq_rsr * vt1sr
             + sgn * (bt1sr - bt1sl)) / ssum
    vt2ss = (sq_rsl * vt2sl + sq_rsr * vt2sr
             + sgn * (bt2sr - bt2sl)) / ssum
    bt1ss = (sq_rsl * bt1sr + sq_rsr * bt1sl
             + sgn * sq_rsl * sq_rsr * (vt1sr - vt1sl)) / ssum
    bt2ss = (sq_rsl * bt2sr + sq_rsr * bt2sl
             + sgn * sq_rsl * sq_rsr * (vt2sr - vt2sl)) / ssum
    vbssl = SM * bn + vt1ss * bt1ss + vt2ss * bt2ss
    essl = esl - sq_rsl * sgn * (vbsl - vbssl)
    essr = esr + sq_rsr * sgn * (vbsr - vbssl)

    def pack(r, vn, vt1, vt2, e, bt1, bt2):
        return [r, r * vn, r * vt1, r * vt2, e, bn, bt1, bt2]

    ul = _cons(rl, vnl, vt1l, vt2l, pl, bn, bt1l, bt2l, g)
    ur = _cons(rr, vnr, vt1r, vt2r, pr, bn, bt1r, bt2r, g)
    usl = pack(rsl, SM, vt1sl, vt2sl, esl, bt1sl, bt2sl)
    usr = pack(rsr, SM, vt1sr, vt2sr, esr, bt1sr, bt2sr)
    ussl = pack(rsl, SM, vt1ss, vt2ss, essl, bt1ss, bt2ss)
    ussr = pack(rsr, SM, vt1ss, vt2ss, essr, bt1ss, bt2ss)
    fl = _flux(rl, vnl, vt1l, vt2l, pl, bn, bt1l, bt2l, g)
    fr = _flux(rr, vnr, vt1r, vt2r, pr, bn, bt1r, bt2r, g)

    out = []
    for k in range(8):
        fsl = fl[k] + SL * (usl[k] - ul[k])
        fsr = fr[k] + SR * (usr[k] - ur[k])
        fssl = fsl + SLs * (ussl[k] - usl[k])
        fssr = fsr + SRs * (ussr[k] - usr[k])
        f = jnp.where(SL > 0.0, fl[k],
                      jnp.where(SLs > 0.0, fsl,
                                jnp.where(SM > 0.0, fssl,
                                          jnp.where(SRs > 0.0, fssr,
                                                    jnp.where(SR > 0.0, fsr,
                                                              fr[k])))))
        out.append(f)
    return jnp.stack(out)
