"""Snapshot writer in the reference's on-disk layout.

One call to :func:`dump_all` produces ``output_NNNNN/`` with the same file
set and record structure as the reference's ``dump_all``
(``amr/output_amr.f90:5-206``): ``info_*.txt``, ``amr_*.outNNNNN``,
``hydro_*.outNNNNN``, optional ``grav_*/part_*`` files, ``header_*.txt``
and the ``*_file_descriptor.txt`` sidecars (``io/dump_utils.f90``).  The
record sequences follow ``backup_amr`` (``amr/output_amr.f90:268-393``),
``backup_hydro`` (``hydro/output_hydro.f90:54-160``), ``backup_part``
(``pm/output_part.f90``) and ``output_info/output_header``
(``amr/output_amr.f90:411-575``) byte for byte, so the reference's own
test oracle (``tests/visu/visu_ramses.py:load_snapshot``) parses our
snapshots unchanged.

The cell-in-oct index convention differs between us (x slowest, numpy
reshape order) and the reference (x fastest, ``ind=1+ix+2*iy+4*iz``); all
per-cell records are permuted to reference order on the way out.
"""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ramses_tpu.io import fortran as frt
from ramses_tpu.units import Units


# ----------------------------------------------------------------------
# cell-order permutation
# ----------------------------------------------------------------------

def ref_cell_perm(ndim: int) -> np.ndarray:
    """perm[ind_ref] = our flat cell offset, where ind_ref runs x-fastest
    (the reference's ``ind_son``) and ours runs x-slowest."""
    n = 1 << ndim
    perm = np.zeros(n, dtype=np.int64)
    for ind in range(n):
        coords = [(ind >> d) & 1 for d in range(ndim)]   # cx, cy, cz
        off = 0
        for d in range(ndim):
            off += coords[d] << (ndim - 1 - d)
        perm[ind] = off
    return perm


# ----------------------------------------------------------------------
# hydro output variables (primitive, hydro/output_hydro.f90:84-146)
# ----------------------------------------------------------------------

def hydro_var_names(cfg) -> List[str]:
    dim_keys = ["x", "y", "z"]
    names = ["density"]
    names += [f"velocity_{dim_keys[d]}" for d in range(cfg.ndim)]
    names += [f"non_thermal_energy_{i + cfg.ndim:02d}"
              for i in range(cfg.nener)]
    names += ["pressure"]
    names += [f"scalar_{i:02d}" for i in range(cfg.npassive)]
    return names


def cons_to_prim_out(u: np.ndarray, cfg) -> np.ndarray:
    """[ncell, nvar] conservative → reference output variables (primitive).

    Mirrors the arithmetic of ``backup_hydro`` exactly: velocity =
    momentum/max(rho,smallr); non-thermal pressures (gamma_rad-1)*e;
    thermal pressure from total minus kinetic minus non-thermal; passive
    scalars per unit mass.
    """
    u = np.asarray(u, dtype=np.float64)
    ndim = cfg.ndim
    rho = np.maximum(u[:, 0], cfg.smallr)
    out = np.empty_like(u)
    out[:, 0] = u[:, 0]
    ekin = np.zeros_like(rho)
    for d in range(ndim):
        out[:, 1 + d] = u[:, 1 + d] / rho
        ekin += 0.5 * u[:, 1 + d] ** 2 / rho
    p = u[:, ndim + 1] - ekin
    for i in range(cfg.nener):
        e = u[:, ndim + 2 + i]
        out[:, ndim + 2 + i] = (cfg.gamma_rad[i] - 1.0) * e
        p = p - e
    out[:, ndim + 1] = (cfg.gamma - 1.0) * p
    for i in range(cfg.npassive):
        j = ndim + 2 + cfg.nener + i
        out[:, j] = u[:, j] / rho
    return out


def prim_out_to_cons(q: np.ndarray, cfg) -> np.ndarray:
    """Inverse of :func:`cons_to_prim_out` (the restart read,
    ``hydro/init_hydro.f90:137+``)."""
    q = np.asarray(q, dtype=np.float64)
    ndim = cfg.ndim
    u = np.empty_like(q)
    rho = q[:, 0]
    u[:, 0] = rho
    ekin = np.zeros_like(rho)
    for d in range(ndim):
        u[:, 1 + d] = rho * q[:, 1 + d]
        ekin += 0.5 * rho * q[:, 1 + d] ** 2
    etot = q[:, ndim + 1] / (cfg.gamma - 1.0) + ekin
    for i in range(cfg.nener):
        e = q[:, ndim + 2 + i] / (cfg.gamma_rad[i] - 1.0)
        u[:, ndim + 2 + i] = e
        etot = etot + e
    u[:, ndim + 1] = etot
    for i in range(cfg.npassive):
        j = ndim + 2 + cfg.nener + i
        u[:, j] = rho * q[:, j]
    return u


# ----------------------------------------------------------------------
# MHD output variables (mhd/output_hydro.f90:82-150: density, velocity,
# B_left, B_right, [non-thermal], thermal_pressure, scalars)
# ----------------------------------------------------------------------

def mhd_var_names(mcfg) -> List[str]:
    dim_keys = ["x", "y", "z"]
    names = ["density"]
    names += [f"velocity_{k}" for k in dim_keys]
    names += [f"B_{k}_left" for k in dim_keys]
    names += [f"B_{k}_right" for k in dim_keys]
    names += ["thermal_pressure"]
    names += [f"scalar_{i:02d}" for i in range(mcfg.npassive)]
    return names


def mhd_rows_to_out(raw: np.ndarray, mcfg) -> np.ndarray:
    """Raw rows [n, nvar+6] = [u | bf_left(3) | bf_right(3)] → the
    reference MHD output columns (``mhd/output_hydro.f90:82-150``)."""
    raw = np.asarray(raw, dtype=np.float64)
    nv = mcfg.nvar
    rho = np.maximum(raw[:, 0], mcfg.smallr)
    out = np.empty((len(raw), 11 + mcfg.npassive))
    out[:, 0] = raw[:, 0]
    ekin = np.zeros_like(rho)
    for c in range(3):
        out[:, 1 + c] = raw[:, 1 + c] / rho
        ekin += 0.5 * raw[:, 1 + c] ** 2 / rho
    emag = 0.5 * (raw[:, 5:8] ** 2).sum(axis=1)
    out[:, 4:7] = raw[:, nv:nv + 3]          # B_left
    out[:, 7:10] = raw[:, nv + 3:nv + 6]     # B_right
    out[:, 10] = (mcfg.gamma - 1.0) * (raw[:, 4] - ekin - emag)
    for i in range(mcfg.npassive):
        out[:, 11 + i] = raw[:, 8 + i] / rho
    return out


def mhd_out_to_state(q: np.ndarray, mcfg):
    """Inverse of :func:`mhd_rows_to_out`: output columns → (u rows
    [n, nvar], bf rows [n, 3, 2]) with cell-centred B rebuilt as the
    face mean (``mhd/init_hydro.f90`` restart read)."""
    q = np.asarray(q, dtype=np.float64)
    n = len(q)
    u = np.zeros((n, mcfg.nvar))
    bf = np.zeros((n, 3, 2))
    rho = q[:, 0]
    u[:, 0] = rho
    ekin = np.zeros(n)
    for c in range(3):
        u[:, 1 + c] = rho * q[:, 1 + c]
        ekin += 0.5 * rho * q[:, 1 + c] ** 2
    bf[:, :, 0] = q[:, 4:7]
    bf[:, :, 1] = q[:, 7:10]
    bc = 0.5 * (bf[:, :, 0] + bf[:, :, 1])
    u[:, 5:8] = bc
    emag = 0.5 * (bc ** 2).sum(axis=1)
    u[:, 4] = q[:, 10] / (mcfg.gamma - 1.0) + ekin + emag
    for i in range(mcfg.npassive):
        u[:, 8 + i] = rho * q[:, 11 + i]
    return u, bf


def snapshot_from_mhd_amr(sim, iout: int = 1) -> Snapshot:
    """Snapshot of an :class:`~ramses_tpu.mhd.amr.MhdAmrSim` — the raw
    rows append both duplicated face fields to the cell state so the
    staggered field round-trips exactly."""
    mcfg = sim.mcfg

    def raw_of(l, nc):
        u = np.asarray(sim.u[l], dtype=np.float64)[:nc]
        bf = np.asarray(sim.bfs[l], dtype=np.float64)[:nc]
        return np.concatenate([u, bf[:, :, 0], bf[:, :, 1]], axis=1)

    return snapshot_from_amr(
        sim, iout, raw_of=raw_of,
        to_out=lambda rows: mhd_rows_to_out(rows, mcfg),
        names=mhd_var_names(mcfg), nvar_raw=mcfg.nvar + 6,
        gamma=mcfg.gamma)


# ----------------------------------------------------------------------
# snapshot tree model
# ----------------------------------------------------------------------

@dataclass
class SnapLevel:
    """One output level: octs in storage order (our sorted-key order)."""
    og: np.ndarray                      # [noct, ndim] int oct coords
    son: np.ndarray                     # [noct, 2^d] global son grid ids,
    #                                     reference ind order, 0 = leaf
    hydro: np.ndarray                   # [noct, 2^d, nvar_out] float64,
    #                                     reference ind order
    grav: Optional[np.ndarray] = None   # [noct, 2^d, ndim+1] phi + forces

    @property
    def noct(self) -> int:
        return len(self.og)


@dataclass
class Snapshot:
    """Everything :func:`dump_all` needs, solver-agnostic."""
    ndim: int
    nlevelmax: int                       # declared max (levelmax)
    levels: Dict[int, SnapLevel]         # 1-based level → data
    boxlen: float
    t: float
    gamma: float
    var_names: List[str]
    units: Units
    levelmin: int = 1
    nstep: int = 0
    nstep_coarse: int = 0
    aexp: float = 1.0
    cosmo: Tuple[float, ...] = (1.0, 0.0, 0.0, 0.045, 1.0, 1.0, 1.0)
    # (omega_m, omega_l, omega_k, omega_b, h0, aexp_ini, boxlen_ini)
    dtold: Optional[np.ndarray] = None
    dtnew: Optional[np.ndarray] = None
    tout: Sequence[float] = (0.0,)
    particles: Optional[dict] = None     # arrays: x,v,m,idp,level,family,tag
    mstar_tot: float = 0.0
    mstar_lost: float = 0.0
    # coarse grid dimensions (&AMR_PARAMS nx, ny, nz — the reference's
    # icoarse/jcoarse/kcoarse extents, amr/init_amr.f90:37-60); cells
    # stay cubic with side boxlen/2^l, the domain extends to
    # (nx, ny, nz)·boxlen
    base: Tuple[int, ...] = (1, 1, 1)

    def grid_id_base(self) -> Dict[int, int]:
        base, tot = {}, 0
        for l in range(1, self.nlevelmax + 1):
            base[l] = tot
            tot += self.levels[l].noct if l in self.levels else 0
        return base

    @property
    def ngrid_total(self) -> int:
        return sum(lv.noct for lv in self.levels.values())


# ----------------------------------------------------------------------
# builders
# ----------------------------------------------------------------------

def _dense_to_level(dense: np.ndarray) -> np.ndarray:
    """Restrict a dense [*sp, nvar] cell array one level down (2^d mean)."""
    nd = dense.ndim - 1
    sl = dense
    for d in range(nd):
        sh = sl.shape
        ns = sh[d] // 2
        sl = sl.reshape(sh[:d] + (ns, 2) + sh[d + 1:])
        sl = sl.mean(axis=d + 1)
    return sl


def _full_level_og(lvl: int, ndim: int, base=None) -> np.ndarray:
    """All oct coords of a complete level, Morton-key sorted order.
    ``base``: coarse-grid dims (nx, ny, nz); level-l oct extents are
    ``base[d] * 2^(l-1)``."""
    from ramses_tpu.amr import keys as kmod
    n = 1 << (lvl - 1)
    if base is None:
        base = (1,) * ndim
    axes = [np.arange(base[d] * n, dtype=np.int64) for d in range(ndim)]
    grids = np.meshgrid(*axes, indexing="ij")
    og = np.stack([g.ravel() for g in grids], axis=1)
    ks = kmod.encode(og, ndim)
    return og[np.argsort(ks, kind="stable")]


def _gather_cells_dense(dense: np.ndarray, og: np.ndarray,
                        perm: np.ndarray) -> np.ndarray:
    """Per-oct cell values from a dense [*sp, nvar] array, ref ind order."""
    from ramses_tpu.amr.tree import cell_offsets
    ndim = og.shape[1]
    offs = cell_offsets(ndim)                       # our flat order
    cc = (2 * og[:, None, :] + offs[None, :, :])    # [noct, 2^d, ndim]
    idx = tuple(cc[..., d] for d in range(ndim))
    vals = dense[idx]                               # [noct, 2^d, nvar]
    return vals[:, perm]


def uniform_levels_from_dense(dense: np.ndarray, lmin: int,
                              ndim: int, base=None) -> Dict[int, SnapLevel]:
    """Scaffolded level set 1..lmin from a dense [*sp, nvar_out] array of
    already-converted output variables (scaffold values by plain mean —
    adequate for the never-leaf coarse levels).  ``base``: coarse-grid
    dims for non-cubic boxes (nx, ny, nz)."""
    from ramses_tpu.amr import keys as kmod
    from ramses_tpu.amr.tree import cell_offsets

    if base is None:
        base = (1,) * ndim
    ncoarse = int(np.prod(base))
    perm = ref_cell_perm(ndim)
    offs = cell_offsets(ndim)
    denses = {lmin: dense}
    for l in range(lmin - 1, 0, -1):
        denses[l] = _dense_to_level(denses[l + 1])
    id_base, tot = {}, 0
    for l in range(1, lmin + 1):
        id_base[l] = tot
        tot += ncoarse * (1 << (l - 1)) ** ndim
    levels: Dict[int, SnapLevel] = {}
    for l in range(1, lmin + 1):
        og = _full_level_og(l, ndim, base)
        hyd = _gather_cells_dense(denses[l], og, perm)
        if l < lmin:
            cc = (2 * og[:, None, :] + offs[None, :, :]).reshape(-1, ndim)
            og1 = _full_level_og(l + 1, ndim, base)
            ks1 = kmod.encode(og1, ndim)
            pos = np.searchsorted(ks1, kmod.encode(cc, ndim))
            son = (id_base[l + 1] + pos + 1).astype(np.int32)
            son = son.reshape(len(og), -1)[:, perm]
        else:
            son = np.zeros((len(og), 1 << ndim), dtype=np.int32)
        levels[l] = SnapLevel(og=og, son=son, hydro=hyd)
    return levels


def snapshot_from_uniform(sim, iout: int = 1) -> Snapshot:
    """Build a snapshot from a single-level :class:`Simulation`.

    Emits the full scaffold hierarchy 1..levelmin (coarser levels fully
    refined, values by conservative restriction) so readers that walk the
    octree see the same structure the reference writes.
    """
    from ramses_tpu.units import units as units_fn

    cfg = sim.cfg
    params = sim.params
    lmin = params.amr.levelmin
    ndim = cfg.ndim
    perm = ref_cell_perm(ndim)
    base = tuple([params.amr.nx, params.amr.ny, params.amr.nz][:ndim])

    u = np.asarray(sim.state.u, dtype=np.float64)   # [nvar, *sp]
    dense = np.moveaxis(u, 0, -1)                   # [*sp, nvar]
    dense_prim = cons_to_prim_out(
        dense.reshape(-1, cfg.nvar), cfg).reshape(dense.shape)
    levels = uniform_levels_from_dense(dense_prim, lmin, ndim, base)

    if getattr(sim.state, "f", None) is not None:
        f = np.asarray(sim.state.f, dtype=np.float64)    # [ndim, *sp]
        phi = np.asarray(sim.phi, dtype=np.float64)[None] \
            if hasattr(sim, "phi") and sim.phi is not None \
            else np.zeros((1,) + f.shape[1:])
        grav_dense = np.moveaxis(np.concatenate([phi, f], axis=0), 0, -1)
        for l, lv in levels.items():
            if l == lmin:
                lv.grav = _gather_cells_dense(grav_dense, lv.og, perm)
            else:
                lv.grav = np.zeros((lv.noct, 1 << ndim, ndim + 1))

    cosmo = getattr(sim, "cosmo", None)
    aexp = (float(cosmo.aexp_of_tau(sim.state.t))
            if cosmo is not None else 1.0)
    un = units_fn(params, cosmo=cosmo, aexp=aexp)
    snap = Snapshot(
        ndim=ndim, nlevelmax=max(params.amr.levelmax, lmin), levels=levels,
        boxlen=float(params.amr.boxlen), t=float(sim.state.t),
        gamma=cfg.gamma, var_names=hydro_var_names(cfg), units=un,
        levelmin=lmin, nstep=int(sim.state.nstep),
        nstep_coarse=int(sim.state.nstep),
        tout=[params.output.tend or 0.0],
        base=base + (1,) * (3 - ndim),
    )
    if cosmo is not None:
        snap.aexp = aexp
        snap.cosmo = (cosmo.omega_m, cosmo.omega_l, cosmo.omega_k,
                      cosmo.omega_b, cosmo.h0, cosmo.aexp_ini,
                      cosmo.boxlen_ini)
    if sim.state.p is not None:
        snap.particles = particles_dict(sim.state.p)
    return snap


def snapshot_from_amr(sim, iout: int = 1, raw_of=None, to_out=None,
                      names: Optional[List[str]] = None,
                      nvar_raw: Optional[int] = None,
                      gamma: Optional[float] = None) -> Snapshot:
    """Build a snapshot from an :class:`AmrSim` (host octree + levels).

    The optional hooks generalize the cell-state handling for solver
    families whose stored state is not the hydro [ncell, nvar] array
    (MHD carries staggered faces): ``raw_of(l, nc)`` returns the raw
    per-cell rows of a level, ``to_out(rows)`` converts raw rows to the
    reference output variables, ``names`` the matching column names,
    ``nvar_raw`` the raw column count.  Defaults implement the hydro
    behaviour (``cons_to_prim_out`` on ``sim.u``).
    """
    from ramses_tpu.amr import keys as kmod
    from ramses_tpu.amr.tree import cell_offsets
    from ramses_tpu.units import units as units_fn

    cfg = sim.cfg
    params = sim.params
    ndim = cfg.ndim
    if raw_of is None:
        # tree_order_cells: under a balance layout (parallel/balance.py)
        # real rows are scattered between pads, so [:nc] is only valid
        # on identity levels
        def raw_of(l, nc):
            rows = sim.tree_order_cells(
                np.asarray(sim.u[l], dtype=np.float64), l)
            return rows[:nc]
    if to_out is None:
        to_out = lambda rows: cons_to_prim_out(rows, cfg)
    names = names if names is not None else hydro_var_names(cfg)
    nvar_raw = nvar_raw if nvar_raw is not None else cfg.nvar
    gamma = gamma if gamma is not None else cfg.gamma
    lmin, lmax = sim.lmin, sim.lmax
    perm = ref_cell_perm(ndim)
    offs = cell_offsets(ndim)
    tree = sim.tree

    # per-level oct sets: scaffold 1..lmin-1 complete, lmin..finest real
    og_of: Dict[int, np.ndarray] = {}
    for l in range(1, lmin):
        og_of[l] = _full_level_og(l, ndim, base=tree.root)
    for l in range(lmin, lmax + 1):
        if tree.has(l):
            og_of[l] = tree.levels[l].og

    id_base, tot = {}, 0
    for l in sorted(og_of):
        id_base[l] = tot
        tot += len(og_of[l])

    # cell values: real levels from device state; scaffold by restriction
    cellvals: Dict[int, np.ndarray] = {}
    for l in range(lmin, lmax + 1):
        if not tree.has(l):
            continue
        m = sim.maps[l]
        nc = m.noct * (1 << ndim)
        cellvals[l] = raw_of(l, nc)
    dense = None
    for l in range(lmin - 1, 0, -1):
        if dense is None:
            # build dense array at lmin (complete base level)
            nv = nvar_raw
            dense = np.zeros(tree.cell_dims(lmin) + (nv,))
            cc = tree.cell_coords(lmin)
            dense[tuple(cc[:, d] for d in range(ndim))] = cellvals[lmin]
            dense = _dense_to_level(dense)
        else:
            dense = _dense_to_level(dense)
        cc = (2 * og_of[l][:, None, :] + offs[None, :, :]).reshape(-1, ndim)
        cellvals[l] = dense[tuple(cc[:, d] for d in range(ndim))]

    levels: Dict[int, SnapLevel] = {}
    for l, og in og_of.items():
        noct = len(og)
        cc = (2 * og[:, None, :] + offs[None, :, :]).reshape(-1, ndim)
        if (l + 1) in og_of:
            ks1 = kmod.encode(og_of[l + 1], ndim)
            pos = np.searchsorted(ks1, kmod.encode(cc, ndim))
            pos = np.clip(pos, 0, len(ks1) - 1)
            hit = ks1[pos] == kmod.encode(cc, ndim)
            son = np.where(hit, id_base[l + 1] + pos + 1, 0).astype(np.int32)
        else:
            son = np.zeros(noct * (1 << ndim), dtype=np.int32)
        hyd = to_out(cellvals[l])
        levels[l] = SnapLevel(
            og=og, son=son.reshape(noct, -1)[:, perm],
            hydro=hyd.reshape(noct, 1 << ndim, -1)[:, perm])

    un = units_fn(params)
    parts = (particles_dict(sim.p)
             if getattr(sim, "p", None) is not None else None)
    trc = getattr(sim, "tracer_x", None)
    if trc is not None and len(trc):
        # gas tracers ride the particle files as massless
        # FAM_GAS_TRACER entries (``pm/output_part.f90`` writes them
        # in the same records).  Ids are the sim's stable per-tracer
        # ids (assigned once at seeding) so cross-snapshot trajectory
        # tracking by id survives particle-population changes; the
        # max-idp fallback only covers legacy sims without them.
        ids = getattr(sim, "tracer_id", None)
        if ids is None:
            id0 = (int(parts["idp"].max()) if parts is not None
                   and len(parts["idp"]) else 0)
            ids = id0 + 1 + np.arange(len(trc))
        tb = _tracer_dict(np.asarray(trc, np.float64),
                          np.asarray(ids))
        parts = (tb if parts is None else
                 {k: np.concatenate([parts[k], tb[k]]) for k in parts})
    # per-level dtold/dtnew from the exact factor-2 subcycling
    # (``amr/update_time.f90`` bookkeeping): restarts need the lmin
    # dtold to complete the pending closing half-kick, and the lmin
    # dtnew (the fused step's emitted CFL dt) to take the SAME next
    # step a continuous run would
    def sub(v):
        return np.array([float(v) * 0.5 ** max(l - lmin, 0)
                         for l in range(1, lmax + 1)])

    dtc = getattr(sim, "_dt_cache", None)
    return Snapshot(
        ndim=ndim, nlevelmax=lmax, levels=levels,
        boxlen=sim.boxlen, t=float(sim.t), gamma=gamma,
        var_names=names, units=un, levelmin=lmin,
        nstep=int(sim.nstep), nstep_coarse=int(sim.nstep),
        tout=[params.output.tend or 0.0], particles=parts,
        dtold=sub(getattr(sim, "dt_old", 0.0)),
        dtnew=sub(dtc) if dtc is not None else None)


def write_sink_csv(path: str, sinks, dmf: Optional[dict] = None) -> None:
    """``sink_NNNNN.csv`` with the reference's column header
    (``pm/output_sink.f90:16-27``); unsampled quantities (angular
    momentum, Bondi diagnostics, SMBH mass) write 0 — the oracle
    (``tests/visu/visu_ramses.py:424-447``) parses any float there."""
    with open(path, "w") as f:
        f.write(" # id,msink,x,y,z,vx,vy,vz,lx,ly,lz,tform,acc_rate,"
                "del_mass,rho_gas,cs**2,etherm,vx_gas,vy_gas,vz_gas,"
                "mbh,dmfsink,level \n")
        f.write(" # 1,m,l,l,l,l t**-1,l t**-1,l t**-1,m l**2 t**-1,"
                "m l**2 t**-1,m l**2 t**-1,t,m t**-1,m,m l**-3,"
                "l**2 t**-2,m l**2 t**-2,l t**-1,l t**-1,l t**-1,"
                "m,m,1\n")
        nd = sinks.x.shape[1]
        for k in range(sinks.n):
            x3 = list(sinks.x[k]) + [0.0] * (3 - nd)
            v3 = list(sinks.v[k]) + [0.0] * (3 - nd)
            dmfk = (dmf or {}).get(int(sinks.idp[k]), 0.0)
            vals = ([sinks.m[k]] + x3 + v3 + [0.0, 0.0, 0.0]
                    + [sinks.tform[k], 0.0, 0.0, 0.0, 0.0, 0.0,
                       0.0, 0.0, 0.0, 0.0, dmfk])
            f.write(f"{int(sinks.idp[k]):10d}"
                    + "".join(f",{v:21.10e}" for v in vals)
                    + f",{1:10d}\n")


def write_stellar_csv(path: str, stellar) -> None:
    """``stellar_NNNNN.csv`` (``pm/output_stellar.f90:16-21``)."""
    with open(path, "w") as f:
        f.write(" # id,mstellar,tform,tlife \n")
        f.write(" # 1,m,t,t\n")
        for k in range(stellar.n):
            f.write(f"{int(stellar.idp[k]):10d},{stellar.m[k]:21.10e},"
                    f"{stellar.tform[k]:21.10e},"
                    f"{stellar.tlife[k]:21.10e}\n")


def _tracer_dict(x: np.ndarray, ids: np.ndarray) -> dict:
    """Massless FAM_GAS_TRACER rows in the :func:`particles_dict`
    layout for the tracer positions ``x`` with per-tracer ids."""
    from ramses_tpu.pm.particles import FAM_GAS_TRACER
    n = len(x)
    z = np.zeros(n)
    return dict(
        x=np.asarray(x, np.float64), v=np.zeros_like(x), m=z.copy(),
        idp=np.asarray(ids).astype(np.int32),
        level=np.full(n, 1, dtype=np.int32),
        family=np.full(n, FAM_GAS_TRACER, dtype=np.int8),
        tag=np.zeros(n, dtype=np.int8), tp=z.copy(), zp=z.copy())


def particles_dict(p) -> dict:
    """Host copies of a :class:`ParticleSet`, active lanes only."""
    act = np.asarray(p.active)
    return dict(
        x=np.asarray(p.x, dtype=np.float64)[act],
        v=np.asarray(p.v, dtype=np.float64)[act],
        m=np.asarray(p.m, dtype=np.float64)[act],
        idp=np.asarray(p.idp)[act].astype(np.int32),
        level=np.full(int(act.sum()), 1, dtype=np.int32),
        family=np.asarray(p.family)[act].astype(np.int8),
        tag=np.zeros(int(act.sum()), dtype=np.int8),
        tp=np.asarray(p.tp, dtype=np.float64)[act],
        zp=np.asarray(p.zp, dtype=np.float64)[act],
    )


# ----------------------------------------------------------------------
# file writers
# ----------------------------------------------------------------------

def _fname(outdir: str, ftype: str, iout: int, icpu: int) -> str:
    return os.path.join(outdir, f"{ftype}_{iout:05d}.out{icpu:05d}")


def write_amr_file(path: str, snap: Snapshot, iout: int,
                   ncpu: int = 1, icpu: int = 1,
                   partial_links: bool = False) -> None:
    """``backup_amr`` record sequence (``amr/output_amr.f90:268-393``).

    ``partial_links``: the snapshot holds only one domain's octs, so
    father/nbor grid ids pointing into other domains cannot be
    resolved — write 0 (the reference's null link) instead of a wrong
    clipped lookup.  Our restart path rebuilds topology from ``xg``
    coordinates and never reads these records."""
    ndim = snap.ndim
    nlevelmax = snap.nlevelmax
    twotondim = 1 << ndim
    twondim = 2 * ndim
    base = tuple(snap.base[:ndim]) + (1,) * (3 - ndim)
    ncoarse = int(np.prod(base))
    ngrid = snap.ngrid_total
    ngridmax = max(ngrid, 1)
    id_base = snap.grid_id_base()
    noutput = max(1, len(snap.tout))
    tout = np.asarray(list(snap.tout) + [0.0] * noutput, dtype=np.float64)
    tout = tout[:noutput]
    dtold = (snap.dtold if snap.dtold is not None
             else np.zeros(nlevelmax))[:nlevelmax]
    dtnew = (snap.dtnew if snap.dtnew is not None
             else np.zeros(nlevelmax))[:nlevelmax]

    numbl = np.zeros((ncpu, nlevelmax), dtype=np.int32)
    headl = np.zeros((ncpu, nlevelmax), dtype=np.int32)
    taill = np.zeros((ncpu, nlevelmax), dtype=np.int32)
    for l in range(1, nlevelmax + 1):
        if l in snap.levels and snap.levels[l].noct > 0:
            n = snap.levels[l].noct
            numbl[icpu - 1, l - 1] = n
            headl[icpu - 1, l - 1] = id_base[l] + 1
            taill[icpu - 1, l - 1] = id_base[l] + n
    numbtot = np.zeros((10, nlevelmax), dtype=np.int32)
    numbtot[0] = numbl.sum(axis=0)
    numbtot[1] = numbl.min(axis=0)
    numbtot[2] = numbl.max(axis=0)

    with open(path, "wb") as f:
        frt.write_ints(f, ncpu)
        frt.write_ints(f, ndim)
        frt.write_ints(f, *base)                         # nx, ny, nz
        frt.write_ints(f, nlevelmax)
        frt.write_ints(f, ngridmax)
        frt.write_ints(f, 0)                             # nboundary
        frt.write_ints(f, ngrid)                         # ngrid_current
        frt.write_reals(f, snap.boxlen)
        frt.write_ints(f, noutput, iout, iout)           # noutput,iout,ifout
        frt.write_record(f, tout)
        frt.write_record(f, np.ones(noutput))            # aout
        frt.write_reals(f, snap.t)
        frt.write_record(f, np.asarray(dtold, dtype=np.float64))
        frt.write_record(f, np.asarray(dtnew, dtype=np.float64))
        frt.write_ints(f, snap.nstep, snap.nstep_coarse)
        frt.write_reals(f, 0.0, 0.0, 0.0)   # einit, mass_tot_0, rho_tot
        om, ol, ok, ob, h0, aexp_ini, boxlen_ini = snap.cosmo
        frt.write_reals(f, om, ol, ok, ob, h0, aexp_ini, boxlen_ini)
        frt.write_reals(f, snap.aexp, 0.0, snap.aexp, 0.0, 0.0)
        # aexp, hexp, aexp_old, epot_tot_int, epot_tot_old
        frt.write_reals(f, 0.0)                          # mass_sph
        # level linked lists (Fortran column-major: cpu fastest)
        frt.write_record(f, headl.T.ravel().astype(np.int32))
        frt.write_record(f, taill.T.ravel().astype(np.int32))
        frt.write_record(f, numbl.T.ravel().astype(np.int32))
        frt.write_record(f, numbtot.T.ravel().astype(np.int32))
        # free memory
        frt.write_ints(f, 0, 0, 0, ngrid, ngrid)
        frt.write_str(f, "hilbert", 128)
        ndomain = ncpu
        bk_max = float(2 ** min(ndim * nlevelmax, 62))
        bound_key = np.linspace(0.0, bk_max, ndomain + 1)
        frt.write_record(f, bound_key)
        # coarse level: each coarse cell's son = the covering level-1
        # oct's grid id (x-fastest cell order, init_amr.f90 ind layout)
        if 1 in snap.levels and snap.levels[1].noct:
            axes = [np.arange(base[d], dtype=np.int64)
                    for d in range(ndim)]
            gr = np.meshgrid(*axes, indexing="ij")
            cc = np.stack([g.ravel() for g in gr], axis=1)
            order = np.zeros(len(cc), dtype=np.int64)    # x-fastest
            for d in range(ndim - 1, -1, -1):
                order = order * base[d] + cc[:, d]
            son_c = np.zeros(ncoarse, dtype=np.int32)
            son_c[order] = _lookup_ids(snap.levels[1].og, cc, 0)
        else:
            son_c = np.zeros(ncoarse, dtype=np.int32)
        frt.write_record(f, son_c)                        # son
        frt.write_record(f, np.zeros(ncoarse, dtype=np.int32))  # flag1
        frt.write_record(f, np.full(ncoarse, icpu, dtype=np.int32))
        # fine levels
        for l in range(1, nlevelmax + 1):
            lv = snap.levels.get(l)
            if lv is None or lv.noct == 0:
                continue
            n = lv.noct
            ids = np.arange(id_base[l] + 1, id_base[l] + n + 1,
                            dtype=np.int32)
            frt.write_record(f, ids)                     # ind_grid
            nxt = np.where(ids < id_base[l] + n, ids + 1, 0).astype(np.int32)
            frt.write_record(f, nxt)                     # next
            prv = np.where(ids > id_base[l] + 1, ids - 1, 0).astype(np.int32)
            frt.write_record(f, prv)                     # prev
            scale = 0.5 ** (l - 1)
            for d in range(ndim):
                frt.write_record(f, (lv.og[:, d] + 0.5) * scale)
            # father cell index
            if l == 1:
                # the coarse cell this oct fills (x-fastest, 1-based)
                acc = np.zeros(n, dtype=np.int64)
                for d in range(ndim - 1, -1, -1):
                    acc = acc * base[d] + lv.og[:, d]
                father = (acc + 1).astype(np.int32)
            elif partial_links:
                father = np.zeros(n, dtype=np.int32)
            else:
                pog = lv.og // 2
                coff = lv.og - 2 * pog
                ind_ref = np.zeros(n, dtype=np.int64)
                for d in range(ndim):
                    ind_ref += coff[:, d] << d           # x fastest
                plv = snap.levels[l - 1]
                pid = _lookup_ids(plv.og, pog, id_base[l - 1])
                father = (ncoarse + ind_ref * ngridmax + pid).astype(np.int32)
            frt.write_record(f, father)
            # nbor: father's 2*ndim neighbour cells,
            # reference order (-x,+x,-y,+y,-z,+z)
            for idir in range(twondim):
                d, sgn = idir // 2, (-1 if idir % 2 == 0 else 1)
                if l == 1:
                    # neighbour COARSE cell index (periodic wrap)
                    cc = lv.og.copy()
                    cc[:, d] = np.mod(cc[:, d] + sgn, base[d])
                    acc = np.zeros(n, dtype=np.int64)
                    for dd in range(ndim - 1, -1, -1):
                        acc = acc * base[dd] + cc[:, dd]
                    frt.write_record(f, (acc + 1).astype(np.int32))
                    continue
                if partial_links:
                    frt.write_record(f, np.zeros(n, dtype=np.int32))
                    continue
                cc = lv.og.copy()
                cc[:, d] += sgn
                ncell = base[d] << (l - 1)
                cc[:, d] = np.mod(cc[:, d], ncell)       # periodic wrap
                pog = cc // 2
                coff = cc - 2 * pog
                ind_ref = np.zeros(n, dtype=np.int64)
                for dd in range(ndim):
                    ind_ref += coff[:, dd] << dd
                plv = snap.levels[l - 1]
                pid = _lookup_ids(plv.og, pog, id_base[l - 1])
                frt.write_record(
                    f, (ncoarse + ind_ref * ngridmax + pid).astype(np.int32))
            # son / cpu_map / flag1 per cell slot (reference ind order)
            for ind in range(twotondim):
                frt.write_record(f, lv.son[:, ind].astype(np.int32))
            for ind in range(twotondim):
                frt.write_record(f, np.full(n, icpu, dtype=np.int32))
            for ind in range(twotondim):
                frt.write_record(f, np.zeros(n, dtype=np.int32))


def _lookup_ids(og_sorted: np.ndarray, q: np.ndarray, base: int) -> np.ndarray:
    """Global grid ids of oct coords ``q`` within a level's sorted oct set."""
    from ramses_tpu.amr import keys as kmod
    ndim = og_sorted.shape[1]
    ks = kmod.encode(og_sorted, ndim)
    kq = kmod.encode(q.astype(np.int64), ndim)
    pos = np.searchsorted(ks, kq)
    pos = np.clip(pos, 0, len(ks) - 1)
    return base + pos + 1


def write_hydro_file(path: str, snap: Snapshot, desc_path: Optional[str],
                     ncpu: int = 1, icpu: int = 1) -> None:
    """``backup_hydro`` record sequence (``hydro/output_hydro.f90:54-160``)."""
    ndim = snap.ndim
    twotondim = 1 << ndim
    nvar = len(snap.var_names)
    with open(path, "wb") as f:
        frt.write_ints(f, ncpu)
        frt.write_ints(f, nvar)
        frt.write_ints(f, ndim)
        frt.write_ints(f, snap.nlevelmax)
        frt.write_ints(f, 0)
        frt.write_reals(f, snap.gamma)
        for l in range(1, snap.nlevelmax + 1):
            for ibound in range(ncpu):
                # a domain's file carries data only in its own slot
                lv = snap.levels.get(l) if ibound == icpu - 1 else None
                ncache = lv.noct if lv is not None else 0
                frt.write_ints(f, l)
                frt.write_ints(f, ncache)
                if ncache == 0:
                    continue
                for ind in range(twotondim):
                    for ivar in range(nvar):
                        frt.write_record(f, lv.hydro[:, ind, ivar])
    if desc_path:
        write_descriptor(desc_path, [(v, "d") for v in snap.var_names])


def write_grav_file(path: str, snap: Snapshot, ncpu: int = 1,
                    icpu: int = 1) -> None:
    """``backup_poisson`` record sequence (``poisson/output_poisson.f90``):
    header ncpu/nvar/nlevelmax/nboundary then per (level, domain)
    ilevel, ncache, and per cell slot phi + ndim force records."""
    ndim = snap.ndim
    twotondim = 1 << ndim
    with open(path, "wb") as f:
        frt.write_ints(f, ncpu)
        frt.write_ints(f, ndim + 1)
        frt.write_ints(f, snap.nlevelmax)
        frt.write_ints(f, 0)
        for l in range(1, snap.nlevelmax + 1):
            for ibound in range(ncpu):
                lv = snap.levels.get(l) if ibound == icpu - 1 else None
                ncache = lv.noct if lv is not None else 0
                frt.write_ints(f, l)
                frt.write_ints(f, ncache)
                if ncache == 0:
                    continue
                g = (lv.grav if lv.grav is not None
                     else np.zeros((ncache, twotondim, ndim + 1)))
                for ind in range(twotondim):
                    for ivar in range(ndim + 1):
                        frt.write_record(f, g[:, ind, ivar])


def write_part_file(path: str, snap: Snapshot, desc_path: Optional[str],
                    ncpu: int = 1,
                    has_star: Optional[bool] = None) -> None:
    """``backup_part`` record sequence (``pm/output_part.f90``).

    ``has_star`` must be decided from the FULL particle set when
    writing multi-domain files — a per-domain decision would make the
    record layout disagree with the shared descriptor."""
    p = snap.particles
    ndim = snap.ndim
    npart = len(p["m"])
    fields: List[Tuple[str, np.ndarray, str]] = []
    dim_keys = ["x", "y", "z"]
    for d in range(ndim):
        fields.append((f"position_{dim_keys[d]}",
                       np.asarray(p["x"][:, d], dtype=np.float64), "d"))
    for d in range(ndim):
        fields.append((f"velocity_{dim_keys[d]}",
                       np.asarray(p["v"][:, d], dtype=np.float64), "d"))
    fields.append(("mass", np.asarray(p["m"], dtype=np.float64), "d"))
    fields.append(("identity", np.asarray(p["idp"], dtype=np.int32), "i"))
    fields.append(("levelp", np.asarray(p["level"], dtype=np.int32), "i"))
    fields.append(("family", np.asarray(p["family"], dtype=np.int8), "b"))
    fields.append(("tag", np.asarray(p["tag"], dtype=np.int8), "b"))
    if has_star is None:
        has_star = bool(np.any(p["family"] == 2)) or np.any(p.get("tp", 0))
    if has_star:
        fields.append(("birth_time",
                       np.asarray(p["tp"], dtype=np.float64), "d"))
        if "zp" in p:
            fields.append(("metallicity",
                           np.asarray(p["zp"], dtype=np.float64), "d"))

    with open(path, "wb") as f:
        frt.write_ints(f, ncpu)
        frt.write_ints(f, ndim)
        frt.write_ints(f, npart)
        frt.write_record(f, np.zeros(4, dtype=np.int32))   # localseed
        frt.write_ints(f, int(np.sum(p["family"] == 2)))   # nstar_tot
        frt.write_reals(f, snap.mstar_tot)
        frt.write_reals(f, snap.mstar_lost)
        frt.write_ints(f, 0)                               # nsink
        for _, arr, _k in fields:
            frt.write_record(f, arr)
    if desc_path:
        write_descriptor(desc_path, [(n, k) for n, _, k in fields])


def write_descriptor(path: str, fields: Sequence[Tuple[str, str]]) -> None:
    """``*_file_descriptor.txt`` (``io/dump_utils.f90:127-139``)."""
    with open(path, "w") as f:
        f.write("# version:  1\n")
        f.write("# ivar, variable_name, variable_type\n")
        for i, (name, kind) in enumerate(fields, start=1):
            f.write(f"{i:2d}, {name}, {kind}\n")


def write_info_file(path: str, snap: Snapshot, ncpu: int = 1) -> None:
    """``output_info`` (``amr/output_amr.f90:411-491``)."""
    un = snap.units
    om, ol, ok, ob, h0, _aexp_ini, _bli = snap.cosmo
    with open(path, "w") as f:
        f.write(f"ncpu        ={ncpu:11d}\n")
        f.write(f"ndim        ={snap.ndim:11d}\n")
        f.write(f"levelmin    ={snap.levelmin:11d}\n")
        f.write(f"levelmax    ={snap.nlevelmax:11d}\n")
        f.write(f"ngridmax    ={max(snap.ngrid_total, 1):11d}\n")
        f.write(f"nstep_coarse={snap.nstep_coarse:11d}\n")
        f.write("\n")
        for k, v in [("boxlen", snap.boxlen), ("time", snap.t),
                     ("aexp", snap.aexp), ("H0", h0), ("omega_m", om),
                     ("omega_l", ol), ("omega_k", ok), ("omega_b", ob),
                     ("unit_l", un.scale_l), ("unit_d", un.scale_d),
                     ("unit_t", un.scale_t)]:
            f.write(f"{k:<12s}={v:23.15E}\n")
        f.write("\n")
        f.write(f"ordering type={'hilbert':>80s}\n")
        f.write("   DOMAIN   ind_min                 ind_max\n")
        bk_max = float(2 ** min(snap.ndim * snap.nlevelmax, 62))
        bounds = np.linspace(0.0, bk_max, ncpu + 1)
        for idom in range(1, ncpu + 1):
            f.write(f"{idom:8d} {bounds[idom - 1]:23.15E}"
                    f" {bounds[idom]:23.15E}\n")


# family keys, pm/pm_commons.f90:84-87 (index -5..5)
FAMILY_KEYS = ["other_tracer", "debris_tracer", "cloud_tracer",
               "star_tracer", "other_tracer", "gas_tracer",
               "DM", "star", "cloud", "debris", "other"]


def write_header_file(path: str, snap: Snapshot) -> None:
    """``output_header`` (``amr/output_amr.f90:496-575``)."""
    counts = np.zeros(11, dtype=np.int64)
    total = 0
    if snap.particles is not None:
        fam = np.asarray(snap.particles["family"])
        total = len(fam)
        for i, f_code in enumerate(range(-5, 6)):
            counts[i] = int(np.sum(fam == f_code))
    with open(path, "w") as f:
        f.write("#" + "Family".rjust(12) + "Count".rjust(10) + "\n")
        for key, cnt in zip(FAMILY_KEYS, counts):
            f.write(key.rjust(13) + f"{cnt:10d}" + "\n")
        f.write("undefined".rjust(13) + f"{total - int(counts.sum()):10d}\n")
        f.write(" Particle fields\n")
        f.write("pos vel mass iord level family tag \n")


def split_snapshot(snap: Snapshot, ncpu: int) -> List[Snapshot]:
    """Split into ``ncpu`` per-domain snapshots: each level's octs cut
    into ``ncpu`` contiguous equal row ranges of the Morton/Hilbert
    storage order — the row-sharded device layout IS the domain
    decomposition (``parallel/amr_sharded.py``), so a sharded run's
    checkpoint writers each own exactly their shard
    (``amr/output_amr.f90:256-400``'s per-cpu files, token ring
    replaced by independent writers).  Particles split the same way."""
    from dataclasses import replace

    def _ranges(n):
        edges = np.linspace(0, n, ncpu + 1).round().astype(int)
        return list(zip(edges[:-1], edges[1:]))

    out = []
    p = snap.particles
    pranges = _ranges(len(p["m"])) if p is not None else None
    for k in range(ncpu):
        levels = {}
        for l, lv in snap.levels.items():
            a, b = _ranges(lv.noct)[k]
            levels[l] = SnapLevel(
                og=lv.og[a:b], son=lv.son[a:b], hydro=lv.hydro[a:b],
                grav=None if lv.grav is None else lv.grav[a:b])
        pk = None
        if p is not None:
            a, b = pranges[k]
            pk = {key: val[a:b] for key, val in p.items()}
        out.append(replace(snap, levels=levels, particles=pk))
    return out


def dump_all(snap: Snapshot, iout: int, base_dir: str = ".",
             namelist_path: Optional[str] = None,
             write_grav: bool = False, ncpu: int = 1,
             extra_dir: Optional[str] = None,
             keep_last: int = 0) -> str:
    """Write ``output_NNNNN/`` with the full reference file set; returns
    the output directory path (``dump_all``, ``amr/output_amr.f90:5-206``).

    The file set is staged into ``output_NNNNN.tmp/``, hashed into a
    ``manifest.json`` and atomically renamed into place — a crash
    mid-dump never leaves a directory that validates as a checkpoint,
    and a stale ``output_NNNNN/`` from an earlier run is replaced, not
    merged.  ``extra_dir`` names a directory of driver extras (movie
    CSVs, clump catalogs, turbulence phases) folded into the stage
    before finalize so they are covered by the manifest too;
    ``keep_last > 0`` rotates older manifest-valid checkpoints away.

    ``ncpu > 1`` writes one file set per domain (multi-domain
    checkpoint); the restore path re-concatenates any domain count onto
    any device count."""
    from ramses_tpu.resilience import checkpoint as ckpt
    from ramses_tpu.resilience import faultinject

    if ncpu > 1 and any(b != 1 for b in snap.base):
        # the domain split orders octs by Hilbert keys over a 2^l cube;
        # non-cubic coarse grids need the reference's multi-root walk
        raise NotImplementedError(
            "multi-domain output with nx,ny,nz != 1 is unsupported "
            f"(base={snap.base}, ncpu={ncpu})")
    final = os.path.join(base_dir, f"output_{iout:05d}")
    outdir = final + ".tmp"
    if os.path.isdir(outdir):
        shutil.rmtree(outdir)     # stale stage from a killed dump
    os.makedirs(outdir)
    suffix = f"{iout:05d}"
    write_info_file(os.path.join(outdir, f"info_{suffix}.txt"), snap,
                    ncpu=ncpu)
    parts = split_snapshot(snap, ncpu) if ncpu > 1 else [snap]
    for icpu, sub in enumerate(parts, start=1):
        write_amr_file(_fname(outdir, "amr", iout, icpu), sub, iout,
                       ncpu=ncpu, icpu=icpu, partial_links=ncpu > 1)
        write_hydro_file(
            _fname(outdir, "hydro", iout, icpu), sub,
            os.path.join(outdir, "hydro_file_descriptor.txt")
            if icpu == 1 else None, ncpu=ncpu, icpu=icpu)
        if write_grav or any(lv.grav is not None
                             for lv in sub.levels.values()):
            write_grav_file(_fname(outdir, "grav", iout, icpu), sub,
                            ncpu=ncpu, icpu=icpu)
        if snap.particles is not None and len(snap.particles["m"]) > 0:
            pfull = snap.particles
            has_star = bool(np.any(pfull["family"] == 2)) \
                or bool(np.any(pfull.get("tp", 0)))
            write_part_file(
                _fname(outdir, "part", iout, icpu), sub,
                os.path.join(outdir, "part_file_descriptor.txt")
                if icpu == 1 else None, ncpu=ncpu, has_star=has_star)
    write_header_file(os.path.join(outdir, f"header_{suffix}.txt"), snap)
    if namelist_path and os.path.exists(namelist_path):
        shutil.copy(namelist_path, os.path.join(outdir, "namelist.txt"))
    if extra_dir and os.path.isdir(extra_dir):
        for name in sorted(os.listdir(extra_dir)):
            shutil.move(os.path.join(extra_dir, name),
                        os.path.join(outdir, name))
        shutil.rmtree(extra_dir, ignore_errors=True)
    out = ckpt.finalize_checkpoint(outdir, final, meta={
        "kind": "output", "iout": int(iout), "nstep": int(snap.nstep),
        "nstep_coarse": int(snap.nstep_coarse), "t": float(snap.t),
        "aexp": float(snap.aexp), "ncpu": int(ncpu),
        "dtold": None if snap.dtold is None
        else [float(x) for x in np.asarray(snap.dtold)]})
    if keep_last > 0:
        ckpt.rotate_checkpoints(base_dir, keep_last, protect=out)
    faultinject.post_dump(out)
    return out
