"""Gadget-1 snapshot reader/writer (dark-matter initial conditions).

Reference: ``pm/gadgetreadfile.f90`` (gadgetreadheader/gadgetreadfile,
``:301``) used by ``pm/init_part.f90`` when ``filetype='gadget'``.
Layout (SnapFormat=1, little-endian Fortran records):

  HEAD  : 256 bytes — npart[6] int32, mass[6] float64, time, redshift,
          flags…, npartTotal[6], …, BoxSize, Omega0, OmegaLambda,
          HubbleParam (float64)
  POS   : 3·N float32 (kpc/h comoving, Gadget convention)
  VEL   : 3·N float32 (km/s · sqrt(a): Gadget internal)
  ID    : N int32/uint32
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Tuple

import numpy as np

from ramses_tpu.io.fortran import read_record as _rec
from ramses_tpu.io.fortran import write_record as _wrec


@dataclass
class GadgetHeader:
    npart: Tuple[int, ...] = (0, 0, 0, 0, 0, 0)
    mass: Tuple[float, ...] = (0.0,) * 6   # 1e10 Msun/h per particle
    time: float = 1.0                      # scale factor for cosmo ICs
    redshift: float = 0.0
    boxsize: float = 0.0                   # kpc/h comoving
    omega0: float = 1.0
    omega_l: float = 0.0
    hubble: float = 0.7                    # h


def read_gadget(path: str):
    """(header, pos [N,3] float64 kpc/h, vel [N,3] float64 km/s·√a,
    ids [N]) — all particle types concatenated (DM ICs carry type 1)."""
    with open(path, "rb") as f:
        raw = _rec(f)
        if len(raw) != 256:
            raise IOError(f"gadget: header record is {len(raw)} bytes")
        npart = struct.unpack("<6i", raw[0:24])
        mass = struct.unpack("<6d", raw[24:72])
        time, redshift = struct.unpack("<2d", raw[72:88])
        # flag_sfr, flag_feedback (2i, 88:96), npartTotal (6i, 96:120),
        # flag_cooling, num_files (2i, 120:128)
        boxsize, omega0, omega_l, hubble = struct.unpack(
            "<4d", raw[128:160])
        n = sum(npart)
        pos = np.frombuffer(_rec(f), dtype="<f4").reshape(n, 3)
        vel = np.frombuffer(_rec(f), dtype="<f4").reshape(n, 3)
        ids = np.frombuffer(_rec(f), dtype="<u4")
    hdr = GadgetHeader(npart, mass, time, redshift, boxsize, omega0,
                       omega_l, hubble)
    return hdr, pos.astype(np.float64), vel.astype(np.float64), ids


def dump_gadget_particles(path: str, p, boxlen: float = 1.0,
                          time: float = 0.0) -> str:
    """Write a sim ParticleSet's *active* lanes as a SnapFormat=1 file
    (the reference's ``savegadget`` flag: each particle output also
    lands as a Gadget snapshot for external tooling).  Positions/
    velocities stay in code units; ndim<3 pads zero columns; the
    header carries one shared mass (type-1 slot, mean of the active
    masses — the format's per-particle MASS block is not written)."""
    act = np.asarray(p.active, dtype=bool)
    x = np.asarray(p.x, dtype=np.float64)[act]
    v = np.asarray(p.v, dtype=np.float64)[act]
    ids = np.asarray(p.idp)[act].astype(np.uint32)
    m = np.asarray(p.m, dtype=np.float64)[act]
    n = int(act.sum())
    if x.ndim == 1:
        x = x[:, None]
        v = v[:, None]
    if x.shape[1] < 3:
        pad = np.zeros((n, 3 - x.shape[1]))
        x = np.concatenate([x, pad], axis=1)
        v = np.concatenate([v, pad], axis=1)
    hdr = GadgetHeader(
        npart=(0, n, 0, 0, 0, 0),
        mass=(0.0, float(m.mean()) if n else 0.0, 0.0, 0.0, 0.0, 0.0),
        time=float(time), boxsize=float(boxlen))
    write_gadget(path, hdr, x, v, ids)
    return path


def write_gadget(path: str, hdr: GadgetHeader, pos: np.ndarray,
                 vel: np.ndarray, ids: np.ndarray):
    """SnapFormat=1 writer (tests + IC tooling)."""
    with open(path, "wb") as f:
        raw = struct.pack("<6i", *hdr.npart)
        raw += struct.pack("<6d", *hdr.mass)
        raw += struct.pack("<2d", hdr.time, hdr.redshift)
        raw += struct.pack("<2i", 0, 0)
        raw += struct.pack("<6i", *hdr.npart)      # npartTotal
        raw += struct.pack("<2i", 0, 1)            # flag_cooling, numfiles
        raw += struct.pack("<4d", hdr.boxsize, hdr.omega0, hdr.omega_l,
                           hdr.hubble)
        raw += b"\x00" * (256 - len(raw))
        _wrec(f, raw)
        _wrec(f, np.ascontiguousarray(pos, dtype="<f4").tobytes())
        _wrec(f, np.ascontiguousarray(vel, dtype="<f4").tobytes())
        _wrec(f, np.ascontiguousarray(ids, dtype="<u4").tobytes())
