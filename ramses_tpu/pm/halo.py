"""Halo-analysis chain: clump membership, unbinding, merger trees.

Reference: ``pm/clump_merger.f90`` (clump properties + output tables),
``pm/unbinding.f90:1-2296`` (iterative particle unbinding against the
clump's own potential), ``pm/merger_tree.f90:1-4312`` (progenitor /
descendant links via shared particle IDs across snapshots).

All passes are host-side numpy over particle arrays — halos are few and
the per-clump work is O(members log members); the expensive part
(density deposition + watershed labelling) already runs on device
(:mod:`ramses_tpu.pm.clumps`).  The unbinding potential uses the
monopole (spherical mass-profile) approximation of the reference
(``unbinding.f90`` 'potential from the cumulative mass profile').
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


# ----------------------------------------------------------------------
# membership
# ----------------------------------------------------------------------

def particle_labels(x: np.ndarray, labels_grid: np.ndarray, dx: float,
                    boxlen: float) -> np.ndarray:
    """Clump label of each particle = label of its NGP cell on the
    dense labelled grid (-1 = unlabelled background)."""
    shape = labels_grid.shape
    nd = x.shape[1]
    idx = tuple(
        np.clip((np.mod(x[:, d], boxlen) / dx).astype(np.int64), 0,
                shape[d] - 1) for d in range(nd))
    return labels_grid[idx]


# ----------------------------------------------------------------------
# unbinding (pm/unbinding.f90)
# ----------------------------------------------------------------------

def _sphere_potential(r: np.ndarray, m: np.ndarray, G: float):
    """Monopole potential at each member's radius from the cumulative
    mass profile: phi(r_i) = -G [ M(<r_i)/r_i + sum_{r_j>r_i} m_j/r_j ]
    (the reference's spherical unbinding potential)."""
    order = np.argsort(r)
    rs = np.maximum(r[order], 1e-12)
    ms = m[order]
    mcum = np.cumsum(ms) - ms            # mass strictly inside r_i
    inv_term = np.cumsum((ms / rs)[::-1])[::-1] - ms / rs  # shells outside
    phi_sorted = -G * ((mcum + ms) / rs + inv_term)
    phi = np.empty_like(phi_sorted)
    phi[order] = phi_sorted
    return phi


def unbind_clump(x: np.ndarray, v: np.ndarray, m: np.ndarray,
                 center: np.ndarray, boxlen: float, G: float = 1.0,
                 periodic: bool = True, max_iter: int = 10,
                 keep_frac_min: float = 0.0):
    """Iterative unbinding of one clump's member particles.

    Returns a bool mask of BOUND members.  Each iteration recomputes
    the bulk velocity and the monopole potential from the currently
    bound set, then strips particles with
    ``0.5|v - vbulk|^2 + phi > 0`` (``unbinding.f90`` iterative mode,
    ``:1400-1600``) until the bound set is stable.
    """
    n = len(m)
    bound = np.ones(n, dtype=bool)
    rel = x - center
    if periodic:
        rel = rel - boxlen * np.round(rel / boxlen)
    r = np.sqrt((rel ** 2).sum(axis=1))
    for _ in range(max_iter):
        nb = bound.sum()
        if nb < 2:
            break
        mtot = m[bound].sum()
        vbulk = (v[bound] * m[bound, None]).sum(0) / mtot
        phi = np.zeros(n)
        phi[bound] = _sphere_potential(r[bound], m[bound], G)
        ekin = 0.5 * ((v - vbulk) ** 2).sum(axis=1)
        new_bound = bound & (ekin + phi < 0.0)
        if new_bound.sum() < max(2, int(keep_frac_min * n)):
            break                        # keep the last stable set
        if new_bound.sum() == nb:
            bound = new_bound
            break
        bound = new_bound
    return bound


# ----------------------------------------------------------------------
# clump catalogue with particle membership
# ----------------------------------------------------------------------

@dataclass
class Halo:
    """One halo/clump with particle membership (the clump_merger table
    row + the unbinding particle lists)."""
    index: int
    mass: float                  # bound mass
    npart: int
    pos: np.ndarray              # mass-weighted bound centre
    vel: np.ndarray              # bulk velocity
    ekin: float                  # internal kinetic energy (bulk removed)
    epot: float                  # monopole potential energy estimate
    ids: np.ndarray              # bound particle IDs (sorted)


def build_catalogue(x: np.ndarray, v: np.ndarray, m: np.ndarray,
                    ids: np.ndarray, plabels: np.ndarray, boxlen: float,
                    G: float = 1.0, periodic: bool = True,
                    unbind: bool = True,
                    npart_min: int = 10) -> List[Halo]:
    """Halo catalogue from labelled particles (one entry per clump with
    >= ``npart_min`` bound members), heaviest first."""
    halos: List[Halo] = []
    for lbl in np.unique(plabels[plabels >= 0]):
        sel = np.nonzero(plabels == lbl)[0]
        if len(sel) < npart_min:
            continue
        xs, vs, ms = x[sel], v[sel], m[sel]
        # provisional centre: mass-weighted with periodic unwrap about
        # the first member
        rel = xs - xs[0]
        if periodic:
            rel = rel - boxlen * np.round(rel / boxlen)
        center = xs[0] + (rel * ms[:, None]).sum(0) / ms.sum()
        if unbind:
            bound = unbind_clump(xs, vs, ms, center, boxlen, G, periodic)
        else:
            bound = np.ones(len(sel), dtype=bool)
        if bound.sum() < npart_min:
            continue
        xs, vs, ms = xs[bound], vs[bound], ms[bound]
        sid = ids[sel][bound]
        mtot = ms.sum()
        rel = xs - center
        if periodic:
            rel = rel - boxlen * np.round(rel / boxlen)
        pos = center + (rel * ms[:, None]).sum(0) / mtot
        if periodic:
            pos = np.mod(pos, boxlen)
        vel = (vs * ms[:, None]).sum(0) / mtot
        r = np.sqrt(((rel - (pos - center)) ** 2).sum(axis=1))
        phi = _sphere_potential(np.maximum(r, 1e-12), ms, G)
        ekin = float(0.5 * (ms * ((vs - vel) ** 2).sum(axis=1)).sum())
        epot = float(0.5 * (ms * phi).sum())
        halos.append(Halo(index=int(lbl), mass=float(mtot),
                          npart=int(bound.sum()), pos=pos, vel=vel,
                          ekin=ekin, epot=epot,
                          ids=np.sort(sid.astype(np.int64))))
    halos.sort(key=lambda h: -h.mass)
    return halos


def write_halo_table(halos: List[Halo], path: str):
    """``clump_masses.txt``-style ascii catalogue."""
    with open(path, "w") as f:
        f.write("# index npart mass x y z vx vy vz ekin epot 2T/|U|\n")
        for h in halos:
            p3 = list(h.pos) + [0.0] * (3 - len(h.pos))
            v3 = list(h.vel) + [0.0] * (3 - len(h.vel))
            vir = 2.0 * h.ekin / max(abs(h.epot), 1e-300)
            f.write(f"{h.index:8d} {h.npart:8d} {h.mass:14.6e} "
                    f"{p3[0]:12.6f} {p3[1]:12.6f} {p3[2]:12.6f} "
                    f"{v3[0]:12.5e} {v3[1]:12.5e} {v3[2]:12.5e} "
                    f"{h.ekin:12.5e} {h.epot:12.5e} {vir:8.3f}\n")


# ----------------------------------------------------------------------
# merger trees (pm/merger_tree.f90)
# ----------------------------------------------------------------------

@dataclass
class TreeLink:
    """One progenitor→descendant link between consecutive catalogues."""
    desc: int                    # descendant halo index (later snapshot)
    prog: int                    # progenitor halo index (earlier)
    shared: int                  # shared particle count
    main: bool                   # True: prog is desc's main progenitor


def link_catalogues(progs: List[Halo], descs: List[Halo],
                    ) -> List[TreeLink]:
    """Progenitor/descendant links via shared particle IDs.

    The reference tracks ``nmost_bound`` tracer particles per clump
    across snapshots and links each progenitor to the descendant
    holding most of them (``merger_tree.f90`` make_merger_tree); here
    every bound particle is a tracer.  The main progenitor of a
    descendant is the one contributing the most shared particles.
    """
    id2prog: Dict[int, int] = {}
    for hp in progs:
        for pid in hp.ids:
            id2prog[int(pid)] = hp.index
    links: List[TreeLink] = []
    for hd in descs:
        counts: Dict[int, int] = {}
        for pid in hd.ids:
            pr = id2prog.get(int(pid))
            if pr is not None:
                counts[pr] = counts.get(pr, 0) + 1
        if not counts:
            continue
        main = max(counts, key=lambda k: counts[k])
        for pr, c in sorted(counts.items(), key=lambda kv: -kv[1]):
            links.append(TreeLink(desc=hd.index, prog=pr, shared=c,
                                  main=(pr == main)))
    return links


class MergerTree:
    """Accumulates catalogues over outputs and writes the tree table
    (``mergertree_txt`` output of ``merger_tree.f90``)."""

    def __init__(self):
        self.snapshots: List[Tuple[float, List[Halo]]] = []
        self.links: List[Tuple[int, List[TreeLink]]] = []

    def add_snapshot(self, t: float, halos: List[Halo]):
        self.snapshots.append((t, halos))
        if len(self.snapshots) > 1:
            prev = self.snapshots[-2][1]
            self.links.append((len(self.snapshots) - 1,
                               link_catalogues(prev, halos)))

    def progenitors(self, snap: int, halo_index: int) -> List[TreeLink]:
        """Links into ``halo_index`` of snapshot ``snap`` (1-based on
        the second snapshot onward)."""
        for s, links in self.links:
            if s == snap:
                return [l for l in links if l.desc == halo_index]
        return []

    def write(self, path: str):
        with open(path, "w") as f:
            f.write("# snap desc_index prog_index shared main\n")
            for s, links in self.links:
                for l in links:
                    f.write(f"{s:6d} {l.desc:8d} {l.prog:8d} "
                            f"{l.shared:8d} {int(l.main):2d}\n")
