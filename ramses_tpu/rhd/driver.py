"""SRHD simulation driver with region ICs (the rhd test-suite shapes:
shock tubes and blast waves, ``rhd/test_suite/``)."""

from __future__ import annotations

import time
from contextlib import nullcontext
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.config import Params
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.rhd import core, uniform as ru
from ramses_tpu.rhd.core import NCOMP, RhdStatic
from ramses_tpu.telemetry import make_telemetry, sim_run_info
from ramses_tpu.telemetry import screen as telemetry_screen


def rhd_region_prims(xc, p: Params, cfg: RhdStatic):
    """Primitive state [nvar, *shape] from &INIT_PARAMS regions at the
    given coordinate arrays ``xc`` (d, u/v/w = velocities in units of c,
    P) — the rhd test-suite ``condinit`` on arbitrary cell centres (the
    AMR driver passes flat per-level centre lists).  A patch ``condinit``
    hook replaces it (the rhd ``condinit.f90`` shadowing point)."""
    from ramses_tpu import patch
    hk = patch.hook("condinit")
    if hk is not None:
        return np.asarray(hk(xc, None, p, cfg))
    init = p.init
    ndim = cfg.ndim
    q = np.zeros((cfg.nvar,) + tuple(xc[0].shape))
    q[0] = cfg.smallr
    q[4] = cfg.smallp
    vels = [init.u_region, init.v_region, init.w_region]
    centers = [init.x_center, init.y_center, init.z_center]
    lengths = [init.length_x, init.length_y, init.length_z]
    for k in range(init.nregion):
        en = float(init.exp_region[k])
        if en < 10.0:
            r = sum((2.0 * np.abs(xc[d] - centers[d][k]) / lengths[d][k])
                    ** en for d in range(ndim)) ** (1.0 / en)
        else:
            r = np.maximum.reduce(
                [2.0 * np.abs(xc[d] - centers[d][k]) / lengths[d][k]
                 for d in range(ndim)])
        m = r < 1.0
        q[0][m] = init.d_region[k]
        for c in range(NCOMP):
            q[1 + c][m] = vels[c][k]
        q[4][m] = init.p_region[k]
    return q


def rhd_condinit(shape, dx: float, p: Params, cfg: RhdStatic):
    """Conservative ICs from &INIT_PARAMS regions on a uniform grid."""
    axes = [(np.arange(n) + 0.5) * dx for n in shape]
    xc = np.meshgrid(*axes, indexing="ij")
    q = rhd_region_prims(xc, p, cfg)
    return np.asarray(core.prim_to_cons(jnp.asarray(q), cfg))


class RhdSimulation:
    """Uniform-grid special-relativistic run."""

    def __init__(self, params: Params, dtype=jnp.float64):
        self.params = params
        self.cfg = RhdStatic.from_params(params)
        base = [params.amr.nx, params.amr.ny, params.amr.nz][:params.ndim]
        if any(b != 1 for b in base):
            # this solver family builds cubic grids; only the hydro
            # uniform driver supports non-cubic coarse boxes
            raise NotImplementedError(
                f"SRHD requires nx=ny=nz=1 (got {base})")
        n = 2 ** params.amr.levelmin
        shape = tuple([n] * params.ndim)
        self.dx = params.amr.boxlen / n
        spec = bmod.BoundarySpec.from_params(params)
        bc_kinds = tuple((f[0].kind, f[1].kind) for f in spec.faces)
        for lo, hi in bc_kinds:
            for k in (lo, hi):
                if k not in (bmod.PERIODIC, bmod.OUTFLOW):
                    raise NotImplementedError(
                        "rhd boundaries: periodic/outflow only")
        self.grid = ru.RhdGrid(cfg=self.cfg, shape=shape, dx=self.dx,
                               bc_kinds=bc_kinds)
        self.u = jnp.asarray(rhd_condinit(shape, self.dx, params,
                                          self.cfg), dtype=dtype)
        self.t = 0.0
        self.nstep = 0
        # perf accounting (mus/pt, adaptive_loop.f90:204-212) — the
        # hydro/mhd uniform drivers track the same pair
        self.cell_updates = 0
        self.wall_s = 0.0
        self.telemetry = make_telemetry(params)
        from ramses_tpu.resilience.faultinject import FaultInjector
        from ramses_tpu.resilience.stepguard import StepGuard
        self._sguard = StepGuard.from_params(params,
                                             telemetry=self.telemetry)
        self._fault = FaultInjector.from_params(params)
        from ramses_tpu.resilience.watchdog import Watchdog
        self._wd = Watchdog.from_params(params, telemetry=self.telemetry)

    def mus_per_cell_update(self) -> float:
        return 1e6 * self.wall_s / max(self.cell_updates, 1)

    def evolve(self, tend: Optional[float] = None, chunk: int = 16,
               nstepmax: int = 10 ** 9, verbose: bool = False,
               guard=None):
        p = self.params
        tend = tend if tend is not None else (
            p.output.tout[-1] if p.output.tout else p.output.tend)
        tdtype = (jnp.float64 if jax.config.jax_enable_x64
                  else jnp.float32)
        telem = self.telemetry
        if telem.enabled:
            telem.run_info.update(sim_run_info(self))
        while self.t < tend * (1 - 1e-12) and self.nstep < nstepmax:
            if guard is not None and not guard.check():
                break
            n = min(chunk, nstepmax - self.nstep)
            # redo-step guard: run_steps does not donate, so plain
            # references retain the pre-window state for rollback
            prev = ((self.u, self.t, self.nstep)
                    if self._sguard is not None else None)
            if self._fault is not None:
                n = self._fault.clamp_window(self.nstep, n)
                self._fault.maybe_nan(self)
            t0 = time.perf_counter()
            t_before = self.t
            with (self._wd.guard("step") if self._wd is not None
                    else nullcontext()):
                if self._fault is not None:
                    self._fault.maybe_hang(self.nstep)
                u, t, ndone = ru.run_steps(
                    self.grid, self.u, jnp.asarray(self.t, tdtype),
                    jnp.asarray(tend, tdtype), n)
                u.block_until_ready()
                ndone = int(ndone)
            wall = time.perf_counter() - t0
            self.wall_s += wall
            self.u, self.t = u, float(t)
            self.nstep += ndone
            if self._wd is not None:
                self._wd.note(nstep=self.nstep, t=self.t)
            self.cell_updates += ndone * self.grid.ncell
            if prev is not None and not self._sguard.ok(self.t):
                ndone = self._retry_window(prev, tend, tdtype)
            if telem.enabled and ndone:
                telem.record_step(
                    self, dt=(self.t - t_before) / ndone, wall_s=wall,
                    steps=ndone, t=self.t, nstep=self.nstep,
                    chunked=ndone)
            if verbose:
                q = core.cons_to_prim(self.u, self.cfg)
                print(telemetry_screen.step_line(
                    self, dt=((self.t - t_before) / ndone
                              if ndone else None), chunk=ndone,
                    extra=("lor_max="
                           f"{float(jnp.max(core.lorentz(q))):.3f}")))
            if ndone == 0:
                break

    def _retry_window(self, prev, tend, tdtype) -> int:
        """Redo-step ladder after a non-finite window: rollback and halve
        dt per attempt (RhdStatic has no 1D Riemann knob, so there is no
        LLF escalation rung), emergency-dump + abort when exhausted."""
        from ramses_tpu.resilience.stepguard import (StepGuard,
                                                     StepRetryExhausted)
        sg = self._sguard
        u0, t0, nstep0 = prev
        sg.record_trip(self)
        for attempt in range(1, sg.max_retries + 1):
            self.u, self.t, self.nstep = u0, t0, nstep0
            scale = 0.5 ** attempt
            sg.record_rollback(self, attempt, scale, escalated=False)
            tw = time.perf_counter()
            u, t, ndone = ru.run_steps(
                self.grid, u0, jnp.asarray(t0, tdtype),
                jnp.asarray(tend, tdtype), 1, dt_scale=scale)
            u.block_until_ready()
            tf = float(t)
            if StepGuard.ok(tf):
                ndone = int(ndone)
                self.u, self.t = u, tf
                self.nstep = nstep0 + ndone
                self.cell_updates += ndone * self.grid.ncell
                self.wall_s += time.perf_counter() - tw
                sg.record_recovered(self, attempt)
                return ndone
        self.u, self.t, self.nstep = u0, t0, nstep0
        out = None
        try:
            out = self.dump(999, str(self.params.output.output_dir))
        except Exception as e:             # noqa: BLE001 - abort path
            print(f"resilience: emergency dump failed: {e}")
        sg.record_abort(self, out)
        raise StepRetryExhausted(
            f"rhd step at t={t0:.6g} still non-finite after "
            f"{sg.max_retries} retries")

    def prims(self):
        return np.asarray(core.cons_to_prim(self.u, self.cfg))

    # ------------------------------------------------------------------
    # snapshot / restart (the rhd solver family's output_hydro shadow:
    # rho, v/c, P columns, con→prim via the pressure Newton)
    # ------------------------------------------------------------------
    def var_names(self):
        names = ["density", "velocity_x", "velocity_y", "velocity_z",
                 "pressure"]
        return names + [f"scalar_{i:02d}"
                        for i in range(self.cfg.npassive)]

    def dump(self, iout: int = 1, base_dir: str = ".",
             namelist_path: Optional[str] = None) -> str:
        from ramses_tpu.io import snapshot as snapmod
        from ramses_tpu.units import units as units_fn
        cfg, params = self.cfg, self.params
        lmin, ndim = params.amr.levelmin, cfg.ndim
        q = np.asarray(core.cons_to_prim(self.u, cfg), np.float64)
        levels = snapmod.uniform_levels_from_dense(
            np.moveaxis(q, 0, -1), lmin, ndim)
        snap = snapmod.Snapshot(
            ndim=ndim, nlevelmax=max(params.amr.levelmax, lmin),
            levels=levels, boxlen=float(params.amr.boxlen),
            t=float(self.t), gamma=cfg.gamma,
            var_names=self.var_names(), units=units_fn(params),
            levelmin=lmin, nstep=int(self.nstep),
            nstep_coarse=int(self.nstep),
            tout=[params.output.tend or 0.0])
        return snapmod.dump_all(snap, iout, base_dir,
                                namelist_path=namelist_path,
                                keep_last=int(getattr(
                                    params.output, "checkpoint_keep", 0)))

    @classmethod
    def from_snapshot(cls, params: Params, outdir: str,
                      dtype=jnp.float64) -> "RhdSimulation":
        from ramses_tpu.io.restart import restore_uniform
        cfg = RhdStatic.from_params(params)

        def to_cons(q):
            return np.asarray(core.prim_to_cons(jnp.asarray(q.T), cfg),
                              dtype=np.float64).T

        dense, meta, _parts = restore_uniform(outdir, params, cfg,
                                              to_cons=to_cons)
        sim = cls(params, dtype=dtype)
        sim.u = jnp.asarray(dense, dtype=dtype)
        sim.t = float(meta["t"])
        sim.nstep = int(meta["nstep"])
        return sim
