"""Structured run telemetry: the JSONL event log + end-of-run sinks.

The reference's only observability is label-based wallclock timers and
ad-hoc stdout blocks (``amr/update_time.f90:38-56``,
``hydro/write_screen.f90``); this subsystem gives every driver one
:class:`Telemetry` recorder with three sinks:

  1. a JSONL event log — one record per coarse step (run-header /
     run-footer records bracket them) carrying the phase wallclock from
     :class:`ramses_tpu.utils.timers.Timers` labels, µs-per-cell-update
     with subcycle weighting (the reference's ``mus/pt``,
     ``amr/adaptive_loop.f90:204-212``), per-level oct counts,
     ``balance_stats`` imbalance, conservation drift from ``totals()``,
     memory high-water marks, a recompile counter, and captured
     XLA/SPMD warnings;
  2. the RAMSES-style ``write_screen`` console block
     (:mod:`ramses_tpu.telemetry.screen`);
  3. the end-of-run ``output_timer`` breakdown.

Zero overhead when off is the design contract: a disabled recorder is
the shared :data:`NULL` singleton whose methods are no-ops — no host
syncs, no device fetches, no label switches reach an un-instrumented
run, and the chunked fast path (``step_chunk``) reports from chunk
summaries instead of falling back to the per-step slow path.

Enabled from the namelist (&OUTPUT_PARAMS ``telemetry='run.jsonl'``,
``telemetry_interval=N``); rendered by ``tools/telemetry_report.py``.
"""

from __future__ import annotations

import atexit
import json
import os
import time
import warnings as _warnings
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

# keys every kind="step" record must carry (tests + report tool key off
# this tuple; extend it together with _make_step_record)
REQUIRED_STEP_KEYS = (
    "kind", "nstep", "t", "dt", "steps", "wall_s", "phases_s",
    "cell_updates", "mus_per_cell_update", "octs",
    "rss_mb", "device_mb", "rss_hwm_mb", "device_hwm_mb",
    "recompiles", "recompiles_total",
)

# substrings that qualify a Python warning for capture into the event
# log (SPMD partitioner / sharding health — the class of message
# tools/multichip.py greps out of subprocess stderr)
WARN_PATTERNS = (
    "rematerialization", "sharding", "spmd", "all-gather", "all-reduce",
    "donat", "replicat",
)

# ---------------------------------------------------------------------
# process-wide recompile counter (jax.monitoring listener).  Listeners
# cannot be unregistered individually, so exactly one is registered,
# lazily, the first time an ENABLED recorder exists — un-instrumented
# processes never register it.
# ---------------------------------------------------------------------
_COMPILES = {"count": 0, "secs": 0.0}
_listener_installed = False


def _install_compile_listener():
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax import monitoring

        def _on_duration(name, secs, **kw):
            if name.endswith("backend_compile_duration"):
                _COMPILES["count"] += 1
                _COMPILES["secs"] += float(secs)

        monitoring.register_event_duration_secs_listener(_on_duration)
        _listener_installed = True
    except Exception:       # monitoring API drift must not kill a run
        pass


def compile_count() -> int:
    return _COMPILES["count"]


# ---------------------------------------------------------------------
# per-sim probes (host-side only; called from ENABLED recorders)
# ---------------------------------------------------------------------
def cell_updates_per_step(sim) -> int:
    """Subcycle-weighted cell updates of ONE coarse step — the
    reference's ``mus/pt`` denominator (``adaptive_loop.f90:204-212``):
    every level's cells times its substep count ``2^(l-lmin)``."""
    tree = getattr(sim, "tree", None)
    if tree is not None:
        ttd = 2 ** sim.cfg.ndim
        return sum(int(tree.noct(l)) * ttd * (1 << (l - sim.lmin))
                   for l in sim.levels())
    grid = getattr(sim, "grid", None)
    if grid is not None:
        return int(grid.ncell)
    return 0


def mesh_census(sim) -> Dict[int, int]:
    """Per-level oct counts.  A uniform grid is its complete coarse
    level: ``ncell / 2^ndim`` octs at ``levelmin``."""
    tree = getattr(sim, "tree", None)
    if tree is not None:
        return {int(l): int(tree.noct(l)) for l in sim.levels()}
    grid = getattr(sim, "grid", None)
    if grid is not None:
        lmin = int(sim.params.amr.levelmin)
        return {lmin: int(grid.ncell) >> int(sim.cfg.ndim)}
    return {}


def _device_hwm_mb() -> float:
    """Device-memory high-water proxy: accelerator ``memory_stats``
    peak when the backend reports one, else the live-buffer census."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return float(stats["peak_bytes_in_use"]) / 2 ** 20
    except Exception:
        pass
    from ramses_tpu.utils.ops import device_mb
    return device_mb()


# ---------------------------------------------------------------------
# spec + recorder
# ---------------------------------------------------------------------
@dataclass(frozen=True)
class TelemetrySpec:
    """&OUTPUT_PARAMS telemetry keys."""
    path: str = ""                 # JSONL event-log path ('' = off)
    interval: int = 1              # coarse steps per emitted record

    @property
    def enabled(self) -> bool:
        return bool(self.path)

    @classmethod
    def from_params(cls, params) -> "TelemetrySpec":
        out = getattr(params, "output", None)
        return cls(
            path=str(getattr(out, "telemetry", "") or ""),
            interval=max(1, int(getattr(out, "telemetry_interval", 1))))


class NullTelemetry:
    """Disabled recorder: every hook is a no-op (shared singleton).

    Drivers hold a reference unconditionally; the ``enabled`` flag lets
    hot paths skip even the method call.
    """

    enabled = False

    def record_step(self, sim, **kw):
        pass

    def record_chunk(self, sim, ts, dts, n, wall_s, **kw):
        pass

    def record_event(self, kind, **fields):
        pass

    def bind(self, **fields):
        pass

    def mark_resumed(self, outdir, attempt=1):
        pass

    def warn(self, msg, source=""):
        pass

    def close(self, sim=None, **kw):
        pass


NULL = NullTelemetry()


class Telemetry:
    """One run's JSONL event log + screen/output_timer sinks.

    Construct via :func:`make_telemetry`; a disabled spec yields the
    :data:`NULL` singleton instead, so every code path below may assume
    the recorder is live.
    """

    def __init__(self, spec: TelemetrySpec,
                 run_info: Optional[Dict[str, Any]] = None,
                 cons_every: int = 10):
        self.spec = spec
        self.enabled = True
        self.run_info = dict(run_info or {})
        # conservation audits download the whole device state
        # (``totals()``) — amortized over emitted records like the
        # OpsGuard screen block's cons_every
        self.cons_every = max(1, int(cons_every))
        self._fh = None
        self._closed = False
        self._t_open = time.perf_counter()
        self._nstep_rec = 0            # emitted step records
        self._steps_pending = 0        # coarse steps since last record
        self._wall_pending = 0.0
        self._phases_last: Dict[str, float] = {}
        self._compiles_last = 0
        self._rss_hwm = 0.0
        self._dev_hwm = 0.0
        self._cons0: Optional[List[float]] = None
        self._warn_pending: List[Dict[str, str]] = []
        self._nwarn = 0
        self._prev_showwarning = None
        self._append = False           # resume: keep prior attempts' log
        self._event_counts: Dict[str, int] = {}
        # correlation fields (trace_id/job/worker — ramses_tpu/obs)
        # stamped onto every record via setdefault; see bind()
        self._bound: Dict[str, Any] = {}
        # out-of-core residency totals (&AMR_PARAMS offload) — summed
        # from per-step stats, surfaced flat in the run footer
        self._off_totals: Dict[str, int] = {
            "offload_stalls": 0, "offload_prefetches": 0,
            "offload_fetches": 0, "offload_overlapped": 0,
            "offload_bytes_parked": 0, "offload_bytes_fetched": 0,
            "offload_device_hwm_bytes": 0}
        _install_compile_listener()

    # -- sinks ---------------------------------------------------------
    def _write(self, rec: Dict[str, Any]):
        if self._closed:
            return
        if self._fh is None:
            d = os.path.dirname(self.spec.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self.spec.path,
                            "a" if self._append else "w")
            atexit.register(self.close)
            # refresh the halo traffic counters at header-write time:
            # the header lands lazily with the first record, i.e. after
            # the first step traced, so the per-step traced byte counts
            # are populated by now (they are zero at sim construction)
            from ramses_tpu.parallel import dma_halo
            self.run_info.update(dma_halo.traffic_snapshot())
            header = {
                "kind": "run_header",
                "schema_version": SCHEMA_VERSION,
                "time_unix": time.time(),
                "pid": os.getpid(),
                "telemetry_interval": self.spec.interval,
                "run_info": self.run_info,
            }
            for k, v in self._bound.items():
                header.setdefault(k, v)
            self._fh.write(json.dumps(header) + "\n")
        for k, v in self._bound.items():
            rec.setdefault(k, v)
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()               # a killed run still leaves records

    # -- warning capture ----------------------------------------------
    def warn(self, msg: str, source: str = ""):
        """Fold a captured warning (SPMD partitioner, sharding fallback,
        subprocess stderr grep, ...) into the next record."""
        self._nwarn += 1
        if len(self._warn_pending) < 50:
            self._warn_pending.append(
                {"msg": str(msg)[:500], "source": source})

    def install_warning_capture(self):
        """Tee Python warnings matching :data:`WARN_PATTERNS` (or raised
        from ramses_tpu/jax modules) into the event log.  XLA's C++
        warnings go to raw stderr and are folded in by the subprocess
        tools (tools/multichip.py) instead."""
        if self._prev_showwarning is not None:
            return
        prev = _warnings.showwarning

        def _hook(message, category, filename, lineno,
                  file=None, line=None):
            text = str(message)
            low = text.lower()
            if any(p in low for p in WARN_PATTERNS) \
                    or "ramses_tpu" in filename or "jax" in filename:
                self.warn(text, source=f"{filename}:{lineno}")
            prev(message, category, filename, lineno, file, line)

        self._prev_showwarning = prev
        _warnings.showwarning = _hook

    # -- records -------------------------------------------------------
    def _mem_sample(self):
        from ramses_tpu.utils.ops import device_mb, rss_mb
        rss, dev = rss_mb(), device_mb()
        self._rss_hwm = max(self._rss_hwm, rss)
        self._dev_hwm = max(self._dev_hwm, dev, _device_hwm_mb())
        return rss, dev

    def _phase_delta(self, sim) -> Dict[str, float]:
        timers = getattr(sim, "timers", None)
        if timers is None:
            return {}
        snap = timers.snapshot()
        delta = {k: round(v - self._phases_last.get(k, 0.0), 6)
                 for k, v in snap.items()
                 if v - self._phases_last.get(k, 0.0) > 0.0}
        self._phases_last = snap
        return delta

    def _cons_sample(self, sim) -> Optional[Dict[str, float]]:
        if not hasattr(sim, "totals"):
            return None
        import numpy as np
        raw = sim.totals()
        if isinstance(raw, dict):          # uniform-grid totals() dicts
            mass = float(raw.get("mass", 0.0))
            energy = float(raw["energy"]) if "energy" in raw else None
        else:                              # AMR drivers: flat nvar array
            arr = np.asarray(raw)
            mass = float(arr[0])
            ie = getattr(getattr(sim, "cfg", None), "ienergy", None)
            energy = (float(arr[ie])
                      if ie is not None and ie < len(arr) else None)
        if self._cons0 is None:
            self._cons0 = [mass, energy]
        m0 = self._cons0[0] or 1.0
        out = {"mcons": mass,
               "mcons_drift": (mass - self._cons0[0]) / m0}
        if energy is not None and self._cons0[1] is not None:
            e0 = self._cons0[1] or 1.0
            out["econs"] = energy
            out["econs_drift"] = (energy - self._cons0[1]) / e0
        return out

    def record_step(self, sim, dt: Optional[float] = None,
                    wall_s: float = 0.0, steps: int = 1,
                    t: Optional[float] = None,
                    nstep: Optional[int] = None,
                    state_current: bool = True,
                    phases: Optional[Dict[str, float]] = None,
                    chunked: int = 0,
                    extra: Optional[Dict[str, Any]] = None):
        """One coarse step (or an aggregate of ``steps`` fused coarse
        steps the caller could not split).  Emits every
        ``telemetry_interval``-th coarse step; wallclock between
        emissions accumulates onto the next record.

        ``state_current``: False for backfilled mid-chunk records whose
        device state no longer exists — skips the conservation audit.
        """
        self._steps_pending += steps
        self._wall_pending += wall_s
        if self._steps_pending < self.spec.interval:
            return
        nsteps = self._steps_pending
        wall = self._wall_pending
        self._steps_pending = 0
        self._wall_pending = 0.0
        self._nstep_rec += 1
        upd = cell_updates_per_step(sim) * nsteps
        rss, dev = self._mem_sample()
        ncomp = _COMPILES["count"]
        rec = {
            "kind": "step",
            "nstep": int(nstep if nstep is not None
                         else getattr(sim, "nstep", 0)),
            "t": float(t if t is not None else getattr(sim, "t", 0.0)),
            "dt": (float(dt) if dt is not None
                   else float(getattr(sim, "dt_old", 0.0))),
            "steps": int(nsteps),
            "wall_s": round(wall, 6),
            "phases_s": (phases if phases is not None
                         else self._phase_delta(sim)),
            "cell_updates": int(upd),
            "mus_per_cell_update": (round(1e6 * wall / upd, 6)
                                    if upd else None),
            "octs": mesh_census(sim),
            "rss_mb": round(rss, 1),
            "device_mb": round(dev, 1),
            "rss_hwm_mb": round(self._rss_hwm, 1),
            "device_hwm_mb": round(self._dev_hwm, 1),
            "recompiles": ncomp - self._compiles_last,
            "recompiles_total": ncomp,
        }
        self._compiles_last = ncomp
        if rec["phases_s"]:
            # timers on: surface how much of each exchanged slab the
            # overlap split computes behind the in-flight DMA (0.0 on
            # the ppermute path or when shards are stencil-thin)
            from ramses_tpu.parallel import dma_halo
            rec["halo_overlap_frac"] = \
                dma_halo.traffic_snapshot()["halo_overlap_frac"]
        if chunked:
            rec["chunked"] = int(chunked)
        bs = getattr(sim, "balance_stats", None)
        if bs is not None:
            rec["balance"] = {
                "max_cost": float(bs.max_cost),
                "mean_cost": float(bs.mean_cost),
                "imbalance": float(bs.imbalance),
                "nreb": int(getattr(sim, "_rebalance_count", 0)),
            }
        bst = getattr(sim, "block_stats", None)
        if bst and "blocked_frac" in bst:
            # fraction of partial-level octs on the blocked tile sweep
            rec["blocked_frac"] = round(float(bst["blocked_frac"]), 4)
        off = getattr(sim, "_offload", None)
        ost = getattr(off, "last_step_stats", None)
        if ost is not None:
            # out-of-core residency traffic of the step cycle that
            # ENDED with this step (regrid/dt fetches included)
            rec["offload"] = {
                "stalls": int(ost["stalls"]),
                "prefetches": int(ost["prefetches"]),
                "fetches": int(ost["fetches"]),
                "overlap_frac": round(float(ost["overlap_frac"]), 4),
                "bytes_parked": int(ost["bytes_parked"]),
                "bytes_fetched": int(ost["bytes_fetched"]),
                "device_hwm_bytes": int(ost["device_hwm_bytes"]),
            }
            self._off_totals["offload_stalls"] += int(ost["stalls"])
            self._off_totals["offload_prefetches"] += \
                int(ost["prefetches"])
            self._off_totals["offload_fetches"] += int(ost["fetches"])
            self._off_totals["offload_overlapped"] += \
                int(ost["overlapped"])
            self._off_totals["offload_bytes_parked"] += \
                int(ost["bytes_parked"])
            self._off_totals["offload_bytes_fetched"] += \
                int(ost["bytes_fetched"])
            hwm = int(ost["device_hwm_bytes"])
            if hwm > self._off_totals["offload_device_hwm_bytes"]:
                self._off_totals["offload_device_hwm_bytes"] = hwm
        nq = getattr(sim, "quarantined_count", None)
        if nq:
            # member isolation ladder (ensemble engines): evicted
            # members surface in step records, not just fault events
            rec["quarantined"] = int(nq)
        if state_current and (self._nstep_rec - 1) % self.cons_every == 0:
            cons = self._cons_sample(sim)
            if cons is not None:
                rec["cons"] = cons
        if self._warn_pending:
            rec["warnings"] = self._warn_pending
            self._warn_pending = []
        if extra:
            rec.update(extra)
        self._write(rec)

    def record_chunk(self, sim, ts, dts, n: int, wall_s: float,
                     nstep_end: Optional[int] = None):
        """Report ``n`` fused coarse steps from ONE ``step_chunk``
        dispatch — per-step ``(t, dt)`` come from the scan's stacked
        outputs, wallclock and phase time are amortized evenly.  The
        fast path stays a single device program; only this summary
        fetch (already paid by the caller) touches the host."""
        if n <= 0:
            return
        phases = self._phase_delta(sim)
        share = {k: round(v / n, 6) for k, v in phases.items()}
        if nstep_end is None:
            nstep_end = int(getattr(sim, "nstep", n))
        for i in range(n):
            self.record_step(
                sim, dt=float(dts[i]), wall_s=wall_s / n, steps=1,
                t=float(ts[i]), nstep=nstep_end - (n - 1 - i),
                state_current=(i == n - 1), phases=share, chunked=n)

    def record_event(self, kind: str, **fields):
        """Free-form record (tool integrations: multichip dryruns,
        bench summaries, resilience rollback/resume/fault events,
        XLA warning folds)."""
        k = str(kind)
        self._event_counts[k] = self._event_counts.get(k, 0) + 1
        rec = {"kind": k}
        rec.update(fields)
        self._write(rec)

    def bind(self, **fields):
        """Stamp correlation fields (``trace_id``, ``job``,
        ``worker`` — ramses_tpu/obs) onto every subsequent record:
        header, steps, events and footer alike.  Applied via
        ``setdefault`` so an explicit field on any record wins; falsy
        values are dropped so an unstamped legacy job binds nothing."""
        self._bound.update({k: v for k, v in fields.items() if v})

    def mark_resumed(self, outdir: str, attempt: int = 1):
        """Flip the sink to append mode (must run before the first
        write opens the file) and log a ``resume`` event — a supervised
        restart extends the same JSONL log rather than truncating the
        earlier attempts' records."""
        self._append = True
        self.record_event("resume", outdir=str(outdir),
                          attempt=int(attempt))

    # -- end of run ----------------------------------------------------
    def close(self, sim=None, print_timers: bool = True):
        """Write the run-footer record and the ``output_timer``
        breakdown (sink 3).  Idempotent."""
        if self._closed:
            return
        if self._prev_showwarning is not None:
            _warnings.showwarning = self._prev_showwarning
            self._prev_showwarning = None
        timers = getattr(sim, "timers", None) if sim is not None else None
        footer = {
            "kind": "run_footer",
            "time_unix": time.time(),
            "wall_s": round(time.perf_counter() - self._t_open, 3),
            "records": self._nstep_rec,
            "recompiles_total": _COMPILES["count"],
            "compile_s_total": round(_COMPILES["secs"], 3),
            "rss_hwm_mb": round(self._rss_hwm, 1),
            "device_hwm_mb": round(self._dev_hwm, 1),
            "warnings_total": self._nwarn,
        }
        if self._event_counts:
            footer["events"] = dict(self._event_counts)
        off_ran = (sim is not None and getattr(
            getattr(sim, "_offload", None), "last_step_stats", None)
            is not None)
        if off_ran or self._off_totals["offload_fetches"] \
                or self._off_totals["offload_bytes_parked"]:
            footer.update(self._off_totals)
            f = self._off_totals["offload_fetches"]
            footer["offload_overlap_frac"] = round(
                self._off_totals["offload_overlapped"] / f, 4) if f \
                else 1.0
        if sim is not None:
            footer["nstep"] = int(getattr(sim, "nstep", 0))
            footer["t"] = float(getattr(sim, "t", 0.0))
        if timers is not None:
            footer["phases_total_s"] = {
                k: round(v, 6) for k, v in timers.snapshot().items()}
            footer["phase_calls"] = dict(timers.count)
        self._write(footer)
        if self._fh is not None:
            self._fh.close()
        self._closed = True
        if print_timers and timers is not None and timers.acc:
            print(timers.output_timer())


def make_telemetry(params, run_info: Optional[Dict[str, Any]] = None):
    """Driver-side factory: a live :class:`Telemetry` when
    &OUTPUT_PARAMS enables it, else the shared no-op :data:`NULL`."""
    spec = TelemetrySpec.from_params(params)
    if not spec.enabled:
        return NULL
    tel = Telemetry(spec, run_info=run_info)
    tel.install_warning_capture()
    return tel


def sim_run_info(sim) -> Dict[str, Any]:
    """Header metadata shared by all drivers."""
    p = getattr(sim, "params", None)
    info = {
        "driver": type(sim).__name__,
        "ndev": int(getattr(sim, "ndev", 1)),
    }
    if p is not None:
        from ramses_tpu.parallel import dma_halo
        info.update(ndim=int(p.ndim), levelmin=int(p.amr.levelmin),
                    levelmax=int(p.amr.levelmax),
                    boxlen=float(p.amr.boxlen),
                    halo_backend=dma_halo.resolve_backend(
                        getattr(p.amr, "halo_backend", "auto")))
    cfg = getattr(sim, "cfg", None)
    if cfg is not None and hasattr(cfg, "nvar"):
        info["nvar"] = int(cfg.nvar)
    bst = getattr(sim, "block_stats", None)
    if bst and "blocked_frac" in bst:
        info["blocked_frac"] = round(float(bst["blocked_frac"]), 4)
    off = getattr(sim, "_offload", None)
    if off is not None:
        info["offload"] = off.mode
        info["offload_hbm_budget_mb"] = float(off.budget_mb)
    from ramses_tpu import platform
    cs = platform.compile_cache_stats()
    if cs["dir"]:
        info["compile_cache_dir"] = cs["dir"]
        info["compile_cache_hits"] = int(cs["hits"])
        info["compile_cache_misses"] = int(cs["misses"])
    return info
