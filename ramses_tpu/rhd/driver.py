"""SRHD simulation driver with region ICs (the rhd test-suite shapes:
shock tubes and blast waves, ``rhd/test_suite/``)."""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.config import Params
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.rhd import core, uniform as ru
from ramses_tpu.rhd.core import NCOMP, RhdStatic
from ramses_tpu.telemetry import make_telemetry, sim_run_info
from ramses_tpu.telemetry import screen as telemetry_screen


def rhd_region_prims(xc, p: Params, cfg: RhdStatic):
    """Primitive state [nvar, *shape] from &INIT_PARAMS regions at the
    given coordinate arrays ``xc`` (d, u/v/w = velocities in units of c,
    P) — the rhd test-suite ``condinit`` on arbitrary cell centres (the
    AMR driver passes flat per-level centre lists).  A patch ``condinit``
    hook replaces it (the rhd ``condinit.f90`` shadowing point)."""
    from ramses_tpu import patch
    hk = patch.hook("condinit")
    if hk is not None:
        return np.asarray(hk(xc, None, p, cfg))
    init = p.init
    ndim = cfg.ndim
    q = np.zeros((cfg.nvar,) + tuple(xc[0].shape))
    q[0] = cfg.smallr
    q[4] = cfg.smallp
    vels = [init.u_region, init.v_region, init.w_region]
    centers = [init.x_center, init.y_center, init.z_center]
    lengths = [init.length_x, init.length_y, init.length_z]
    for k in range(init.nregion):
        en = float(init.exp_region[k])
        if en < 10.0:
            r = sum((2.0 * np.abs(xc[d] - centers[d][k]) / lengths[d][k])
                    ** en for d in range(ndim)) ** (1.0 / en)
        else:
            r = np.maximum.reduce(
                [2.0 * np.abs(xc[d] - centers[d][k]) / lengths[d][k]
                 for d in range(ndim)])
        m = r < 1.0
        q[0][m] = init.d_region[k]
        for c in range(NCOMP):
            q[1 + c][m] = vels[c][k]
        q[4][m] = init.p_region[k]
    return q


def rhd_condinit(shape, dx: float, p: Params, cfg: RhdStatic):
    """Conservative ICs from &INIT_PARAMS regions on a uniform grid."""
    axes = [(np.arange(n) + 0.5) * dx for n in shape]
    xc = np.meshgrid(*axes, indexing="ij")
    q = rhd_region_prims(xc, p, cfg)
    return np.asarray(core.prim_to_cons(jnp.asarray(q), cfg))


class RhdSimulation:
    """Uniform-grid special-relativistic run."""

    def __init__(self, params: Params, dtype=jnp.float64):
        self.params = params
        self.cfg = RhdStatic.from_params(params)
        base = [params.amr.nx, params.amr.ny, params.amr.nz][:params.ndim]
        if any(b != 1 for b in base):
            # this solver family builds cubic grids; only the hydro
            # uniform driver supports non-cubic coarse boxes
            raise NotImplementedError(
                f"SRHD requires nx=ny=nz=1 (got {base})")
        n = 2 ** params.amr.levelmin
        shape = tuple([n] * params.ndim)
        self.dx = params.amr.boxlen / n
        spec = bmod.BoundarySpec.from_params(params)
        bc_kinds = tuple((f[0].kind, f[1].kind) for f in spec.faces)
        for lo, hi in bc_kinds:
            for k in (lo, hi):
                if k not in (bmod.PERIODIC, bmod.OUTFLOW):
                    raise NotImplementedError(
                        "rhd boundaries: periodic/outflow only")
        self.grid = ru.RhdGrid(cfg=self.cfg, shape=shape, dx=self.dx,
                               bc_kinds=bc_kinds)
        self.u = jnp.asarray(rhd_condinit(shape, self.dx, params,
                                          self.cfg), dtype=dtype)
        self.t = 0.0
        self.nstep = 0
        # perf accounting (mus/pt, adaptive_loop.f90:204-212) — the
        # hydro/mhd uniform drivers track the same pair
        self.cell_updates = 0
        self.wall_s = 0.0
        self.telemetry = make_telemetry(params)

    def mus_per_cell_update(self) -> float:
        return 1e6 * self.wall_s / max(self.cell_updates, 1)

    def evolve(self, tend: Optional[float] = None, chunk: int = 16,
               nstepmax: int = 10 ** 9, verbose: bool = False,
               guard=None):
        p = self.params
        tend = tend if tend is not None else (
            p.output.tout[-1] if p.output.tout else p.output.tend)
        tdtype = (jnp.float64 if jax.config.jax_enable_x64
                  else jnp.float32)
        telem = self.telemetry
        if telem.enabled:
            telem.run_info.update(sim_run_info(self))
        while self.t < tend * (1 - 1e-12) and self.nstep < nstepmax:
            if guard is not None and not guard.check():
                break
            n = min(chunk, nstepmax - self.nstep)
            t0 = time.perf_counter()
            t_before = self.t
            u, t, ndone = ru.run_steps(
                self.grid, self.u, jnp.asarray(self.t, tdtype),
                jnp.asarray(tend, tdtype), n)
            u.block_until_ready()
            wall = time.perf_counter() - t0
            self.wall_s += wall
            ndone = int(ndone)
            self.u, self.t = u, float(t)
            self.nstep += ndone
            self.cell_updates += ndone * self.grid.ncell
            if telem.enabled and ndone:
                telem.record_step(
                    self, dt=(self.t - t_before) / ndone, wall_s=wall,
                    steps=ndone, t=self.t, nstep=self.nstep,
                    chunked=ndone)
            if verbose:
                q = core.cons_to_prim(self.u, self.cfg)
                print(telemetry_screen.step_line(
                    self, dt=((self.t - t_before) / ndone
                              if ndone else None), chunk=ndone,
                    extra=("lor_max="
                           f"{float(jnp.max(core.lorentz(q))):.3f}")))
            if ndone == 0:
                break

    def prims(self):
        return np.asarray(core.cons_to_prim(self.u, self.cfg))

    # ------------------------------------------------------------------
    # snapshot / restart (the rhd solver family's output_hydro shadow:
    # rho, v/c, P columns, con→prim via the pressure Newton)
    # ------------------------------------------------------------------
    def var_names(self):
        names = ["density", "velocity_x", "velocity_y", "velocity_z",
                 "pressure"]
        return names + [f"scalar_{i:02d}"
                        for i in range(self.cfg.npassive)]

    def dump(self, iout: int = 1, base_dir: str = ".",
             namelist_path: Optional[str] = None) -> str:
        from ramses_tpu.io import snapshot as snapmod
        from ramses_tpu.units import units as units_fn
        cfg, params = self.cfg, self.params
        lmin, ndim = params.amr.levelmin, cfg.ndim
        q = np.asarray(core.cons_to_prim(self.u, cfg), np.float64)
        levels = snapmod.uniform_levels_from_dense(
            np.moveaxis(q, 0, -1), lmin, ndim)
        snap = snapmod.Snapshot(
            ndim=ndim, nlevelmax=max(params.amr.levelmax, lmin),
            levels=levels, boxlen=float(params.amr.boxlen),
            t=float(self.t), gamma=cfg.gamma,
            var_names=self.var_names(), units=units_fn(params),
            levelmin=lmin, nstep=int(self.nstep),
            nstep_coarse=int(self.nstep),
            tout=[params.output.tend or 0.0])
        return snapmod.dump_all(snap, iout, base_dir,
                                namelist_path=namelist_path)

    @classmethod
    def from_snapshot(cls, params: Params, outdir: str,
                      dtype=jnp.float64) -> "RhdSimulation":
        from ramses_tpu.io.restart import restore_uniform
        cfg = RhdStatic.from_params(params)

        def to_cons(q):
            return np.asarray(core.prim_to_cons(jnp.asarray(q.T), cfg),
                              dtype=np.float64).T

        dense, meta, _parts = restore_uniform(outdir, params, cfg,
                                              to_cons=to_cons)
        sim = cls(params, dtype=dtype)
        sim.u = jnp.asarray(dense, dtype=dtype)
        sim.t = float(meta["t"])
        sim.nstep = int(meta["nstep"])
        return sim
