"""Supervised retry-with-resume loop for namelist-driven runs.

``supervise(build, drive, params, ...)`` runs a bounded attempt loop:
attempt 1 resolves the restart directory from the namelist
(``nrestart``/``auto_resume``), later attempts always pick the newest
manifest-valid checkpoint — so a SIGTERM/preemption mid-run (whose
OpsGuard stop path flushes queued dumps) resumes from the last good
output instead of failing the allocation.  Backoff between attempts is
exponential and capped; :func:`backoff_delay` is shared with bench.py
so both supervisors pace retries identically.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ramses_tpu.resilience.checkpoint import (latest_valid_checkpoint,
                                              resolve_restart_dir)


def backoff_delay(attempt: int, base: float = 1.0,
                  cap: float = 30.0) -> float:
    """Exponential backoff (attempt 1 -> base, doubling), capped."""
    return float(min(cap, base * (2.0 ** max(0, int(attempt) - 1))))


def _sim_t(sim) -> float:
    st = getattr(sim, "state", None)
    if st is not None and hasattr(st, "t"):
        return float(st.t)
    return float(getattr(sim, "t", 0.0))


def _sim_nstep(sim) -> int:
    st = getattr(sim, "state", None)
    if st is not None and hasattr(st, "nstep"):
        return int(st.nstep)
    return int(getattr(sim, "nstep", 0))


def run_complete(sim, params, tend: Optional[float] = None) -> bool:
    """Did the run reach its configured end (tend or nstepmax)?

    A sim may own the answer: when it defines a ``run_complete``
    method that wins (the ensemble engine does — "complete" there
    means every *member* reached its own tend/budget, which the
    scalar t/nstep probes below cannot express)."""
    own = getattr(sim, "run_complete", None)
    if callable(own):
        return bool(own(params, tend=tend))
    run = getattr(params, "run", None)
    nmax = getattr(run, "nstepmax", None)
    if nmax is not None and int(nmax) > 0 \
            and _sim_nstep(sim) >= int(nmax):
        return True
    end = tend
    if end is None:
        touts = getattr(getattr(params, "output", None), "tout",
                        None) or ()
        end = max(touts) if touts else None
    if end is None:
        return True               # nothing to measure against
    # Round-off slack: the drivers stop at t >= tend - eps*tend.
    return _sim_t(sim) >= float(end) * (1.0 - 1e-12) - 1e-300


def supervise(build: Callable, drive: Callable, params,
              base_dir: str = ".", max_attempts: int = 3,
              backoff_s: float = 1.0, tend: Optional[float] = None,
              log: Callable = print):
    """Run ``drive(build(restart_dir))`` until complete or attempts
    are exhausted.

    ``build(restart_dir)`` constructs the simulation (fresh when
    restart_dir is None, else restored from that checkpoint);
    ``drive(sim)`` evolves it and returns normally on a clean stop
    (including an OpsGuard-handled SIGTERM).  Returns the final sim.
    """
    max_attempts = max(1, int(max_attempts))
    last_err = None
    sim = None
    for attempt in range(1, max_attempts + 1):
        if attempt == 1:
            restart = resolve_restart_dir(params, base_dir=base_dir,
                                          log=log)
        else:
            restart = latest_valid_checkpoint(base_dir, log=log)
            if restart is not None:
                log(f"resilience: attempt {attempt}/{max_attempts} "
                    f"resuming from {restart}")
            else:
                log(f"resilience: attempt {attempt}/{max_attempts} "
                    "found no valid checkpoint; restarting fresh")
        sim = build(restart)
        tel = getattr(sim, "telemetry", None)
        if restart is not None and tel is not None:
            try:
                tel.mark_resumed(restart, attempt)
            except AttributeError:
                pass
        try:
            drive(sim)
            last_err = None
        except Exception as e:   # noqa: BLE001 — supervisor boundary
            last_err = e
            log(f"resilience: attempt {attempt} failed: {e!r}")
        if last_err is None and run_complete(sim, params, tend=tend):
            return sim
        if attempt == max_attempts:
            break
        # Interrupted (stop flag / SIGTERM / crash): close this
        # attempt's telemetry so the resumed one appends cleanly.
        if tel is not None:
            try:
                tel.close(sim, print_timers=False)
            except Exception:
                pass
        delay = backoff_delay(attempt, base=backoff_s)
        log(f"resilience: run incomplete at nstep={_sim_nstep(sim)} "
            f"t={_sim_t(sim):.6g}; retrying in {delay:.1f}s")
        time.sleep(delay)
    if last_err is not None:
        raise last_err
    log(f"resilience: giving up after {max_attempts} attempts "
        f"(nstep={_sim_nstep(sim)} t={_sim_t(sim):.6g})")
    return sim
