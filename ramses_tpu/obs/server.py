"""Streaming results/metrics HTTP service over a queue directory.

The fleet-facing half of ROADMAP item 3(d): consumers hit *artifacts*
— record JSONs, telemetry JSONL tails, manifest-validated checkpoint
files — never devices.  The server is a daemon-threaded stdlib
``http.server`` reading the same files the queue machinery writes, so
arming it adds zero device fetches to a running worker (pinned in
``tests/test_obs.py``).

Endpoints::

    GET  /healthz                     liveness + queue counts
    GET  /metrics                     Prometheus text exposition
    GET  /jobs                        queue census (per-state summaries)
    GET  /jobs/<id>                   full record (failure_log included)
    GET  /jobs/<id>/telemetry?offset=N   resumable JSONL tail
    GET  /jobs/<id>/artifacts         manifest-validated listing
    GET  /jobs/<id>/artifacts/<path>  file bytes (Range supported)
    POST /jobs/<id>/profile           arm on-demand device profiling

The telemetry tail serves whole lines only from byte ``offset`` and
returns the next offset in ``X-Telemetry-Offset`` — a consumer that
always resumes from the returned offset sees every record exactly
once.  ``offset`` beyond the current size means the file was rotated
(a fresh attempt truncated it): the tail restarts from 0 with
``X-Telemetry-Rotated: 1``.

Started with ``--obs-port`` on a serve worker, or standalone via
``python -m ramses_tpu --obs <queue_dir>`` (scraping a queue needs no
worker at all).  Pointed at a plain run output dir (no ``queued/``)
it serves that single run as pseudo-job ``run``.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from ramses_tpu.ensemble import queue as jq
from ramses_tpu.obs import metrics as om
from ramses_tpu.obs.profile import PROFILE_FLAG
from ramses_tpu.resilience.checkpoint import (MANIFEST_NAME,
                                              read_manifest_meta,
                                              validate_checkpoint)

#: cap on one telemetry-tail response; a consumer catches up across
#: requests by resuming from X-Telemetry-Offset
MAX_TAIL_BYTES = 4 << 20

_JOB_ID_RE = re.compile(r"^[A-Za-z0-9_.:-]{1,200}$")


def tail_jsonl(path: str, offset: int,
               max_bytes: int = MAX_TAIL_BYTES
               ) -> Tuple[bytes, int, bool]:
    """Whole-line window of ``path`` from byte ``offset``.  Returns
    ``(data, next_offset, rotated)`` — exactly-once semantics when the
    caller always resumes from ``next_offset``."""
    size = os.path.getsize(path)
    rotated = False
    if offset > size or offset < 0:
        offset, rotated = 0, True
    with open(path, "rb") as f:
        f.seek(offset)
        data = f.read(max_bytes)
    cut = data.rfind(b"\n")
    data = data[:cut + 1] if cut >= 0 else b""
    return data, offset + len(data), rotated


class ObsServer:
    """Threaded observability server over ``root`` (a queue dir, or
    any run output dir in single-run mode)."""

    def __init__(self, root: str, port: int = 0,
                 bind: str = "127.0.0.1", log=None):
        self.root = os.path.abspath(root)
        self.bind = bind
        self.log = log
        # queue mode iff the directory has (or can be) a queue layout;
        # a plain output dir is served as single pseudo-job "run"
        self.queue_mode = os.path.isdir(os.path.join(self.root,
                                                     "queued"))
        self._httpd = ThreadingHTTPServer((bind, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.obs = self          # handler back-reference
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        return f"http://{self.bind}:{self.port}"

    def start(self) -> "ObsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ramses-obs",
            daemon=True)
        self._thread.start()
        if self.log is not None:
            self.log(f"obs: serving {self.root} on {self.url}")
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- data access ---------------------------------------------------
    def job_states(self) -> List[Tuple[str, str]]:
        """``[(job_id, state), ...]`` across the lifecycle dirs."""
        if not self.queue_mode:
            return [("run", "running")]
        out: List[Tuple[str, str]] = []
        for state in jq.STATES:
            d = os.path.join(self.root, state)
            try:
                names = sorted(os.listdir(d))
            except OSError:
                continue
            out.extend((n[:-len(".json")], state) for n in names
                       if n.endswith(".json"))
        return out

    def job_record(self, job_id: str) -> Optional[Dict[str, Any]]:
        if not self.queue_mode:
            return {"id": "run", "kind": "run"} \
                if job_id == "run" else None
        job = jq.job_status(self.root, job_id)
        if job is None:
            return None
        rec = dict(job.record)
        rec["state"] = job.state
        try:
            rec["heartbeat_age_s"] = round(
                time.time() - os.path.getmtime(job.path), 3)
        except OSError:
            pass
        return rec

    def results_dir(self, job_id: str) -> str:
        if not self.queue_mode:
            return self.root
        return jq.results_dir(self.root, job_id)

    def telemetry_path(self, job_id: str) -> str:
        rdir = self.results_dir(job_id)
        path = os.path.join(rdir, "telemetry.jsonl")
        if not self.queue_mode and not os.path.isfile(path):
            # single-run mode: any telemetry JSONL in the output dir
            try:
                cand = sorted(n for n in os.listdir(rdir)
                              if n.endswith(".jsonl"))
            except OSError:
                cand = []
            if cand:
                path = os.path.join(rdir, cand[0])
        return path

    def artifacts(self, job_id: str) -> Dict[str, Any]:
        """Manifest-validated checkpoint/profile dirs + loose files in
        the job's results dir.  Validation is the cheap existence+size
        scan — a byte-level audit is the consumer's call (the manifest
        carries the sha256 table)."""
        rdir = self.results_dir(job_id)
        dirs: List[Dict[str, Any]] = []
        loose: List[Dict[str, Any]] = []
        try:
            names = sorted(os.listdir(rdir))
        except OSError:
            names = []
        for name in names:
            p = os.path.join(rdir, name)
            if os.path.isdir(p):
                if not os.path.isfile(os.path.join(p, MANIFEST_NAME)):
                    continue   # staging dir / pre-atomic dump: not served
                ok, reason = validate_checkpoint(p, verify_hash=False)
                files = []
                for root, _d, fnames in os.walk(p):
                    for fn in sorted(fnames):
                        fp = os.path.join(root, fn)
                        files.append({
                            "path": os.path.relpath(fp, rdir),
                            "size": os.path.getsize(fp)})
                dirs.append({"name": name, "valid": bool(ok),
                             "reason": reason,
                             "meta": read_manifest_meta(p),
                             "files": files})
            elif os.path.isfile(p):
                loose.append({"path": name, "size": os.path.getsize(p)})
        return {"job": job_id, "results_dir": rdir,
                "checkpoints": dirs, "files": loose}

    def artifact_file(self, job_id: str, rel: str) -> Optional[str]:
        """Resolve one served file, refusing any path that escapes the
        job's results dir (symlinks included)."""
        rdir = os.path.realpath(self.results_dir(job_id))
        path = os.path.realpath(os.path.join(rdir, rel))
        if path != rdir and not path.startswith(rdir + os.sep):
            return None
        return path if os.path.isfile(path) else None

    def arm_profile(self, job_id: str,
                    req: Dict[str, Any]) -> Dict[str, Any]:
        """Write the ``profile_request.json`` flag the worker's chunk
        loop polls (ramses_tpu/obs/profile.py)."""
        rdir = self.results_dir(job_id)
        os.makedirs(rdir, exist_ok=True)
        chunks = max(1, int(req.get("chunks", 1)))
        flag = os.path.join(rdir, PROFILE_FLAG)
        tmp = flag + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"chunks": chunks,
                       "requested_unix": time.time()}, f)
        os.replace(tmp, flag)
        return {"armed": True, "job": job_id, "chunks": chunks,
                "flag": flag}


class _Handler(BaseHTTPRequestHandler):
    server_version = "ramses-obs/1"
    protocol_version = "HTTP/1.1"

    # route table kept in one place so OPTIONS/errors stay honest
    def do_GET(self):          # noqa: N802 — http.server API
        self._route("GET")

    def do_POST(self):         # noqa: N802
        self._route("POST")

    def log_message(self, fmt, *args):
        log = self.server.obs.log
        if log is not None:
            log(f"obs: {self.address_string()} {fmt % args}")

    # -- responses -----------------------------------------------------
    def _send(self, code: int, body: bytes, ctype: str,
              headers: Optional[Dict[str, str]] = None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj: Any, code: int = 200,
              headers: Optional[Dict[str, str]] = None):
        body = (json.dumps(obj, indent=1) + "\n").encode()
        self._send(code, body, "application/json", headers)

    def _error(self, code: int, msg: str):
        self._json({"error": msg}, code=code)

    # -- routing -------------------------------------------------------
    def _route(self, method: str):
        obs: ObsServer = self.server.obs
        try:
            url = urlsplit(self.path)
            parts = [p for p in url.path.split("/") if p]
            query = {k: v[-1] for k, v in parse_qs(url.query).items()}
            if method == "GET" and parts == ["healthz"]:
                return self._healthz(obs)
            if method == "GET" and parts == ["metrics"]:
                return self._metrics(obs)
            if method == "GET" and parts == ["jobs"]:
                return self._jobs(obs)
            if len(parts) >= 2 and parts[0] == "jobs":
                job_id = parts[1]
                if not _JOB_ID_RE.match(job_id):
                    return self._error(400, "bad job id")
                if obs.job_record(job_id) is None:
                    return self._error(404, f"unknown job {job_id}")
                rest = parts[2:]
                if method == "GET" and not rest:
                    return self._json(obs.job_record(job_id))
                if method == "GET" and rest == ["telemetry"]:
                    return self._telemetry(obs, job_id, query)
                if method == "GET" and rest == ["artifacts"]:
                    return self._json(obs.artifacts(job_id))
                if method == "GET" and rest \
                        and rest[0] == "artifacts":
                    return self._file(obs, job_id, "/".join(rest[1:]))
                if method == "POST" and rest == ["profile"]:
                    return self._profile(obs, job_id, query)
            self._error(404, f"no route for {method} {url.path}")
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001 — server must not die
            try:
                self._error(500, repr(e))
            except Exception:
                pass

    # -- endpoints -----------------------------------------------------
    def _healthz(self, obs: ObsServer):
        out = {"ok": True, "root": obs.root,
               "mode": "queue" if obs.queue_mode else "results",
               "time_unix": time.time()}
        if obs.queue_mode:
            out["queue"] = jq.queue_counts(obs.root)
        self._json(out)

    def _metrics(self, obs: ObsServer):
        if obs.queue_mode:
            text = om.render_queue_metrics(obs.root)
        else:
            text = om.render([om.Family(
                "ramses_obs_results_mode", "gauge",
                "Server is in single-run results mode.").add(1)])
        self._send(200, text.encode(),
                   "text/plain; version=0.0.4; charset=utf-8")

    def _jobs(self, obs: ObsServer):
        jobs = []
        for job_id, state in obs.job_states():
            rec = obs.job_record(job_id) or {}
            entry = {"id": job_id, "state": state,
                     "kind": rec.get("kind", "run"),
                     "attempts": rec.get("attempts", 0),
                     "trace_id": rec.get("trace_id", ""),
                     "worker": rec.get("worker", ""),
                     "failures": len(rec.get("failure_log") or [])}
            result = rec.get("result") or {}
            if result.get("partial"):
                entry["quarantined"] = len(
                    result.get("failed_members") or [])
            jobs.append(entry)
        out: Dict[str, Any] = {"jobs": jobs}
        if obs.queue_mode:
            out["counts"] = jq.queue_counts(obs.root)
        self._json(out)

    def _telemetry(self, obs: ObsServer, job_id: str,
                   query: Dict[str, str]):
        path = obs.telemetry_path(job_id)
        try:
            offset = int(query.get("offset", "0"))
        except ValueError:
            return self._error(400, "offset must be an integer")
        if not os.path.isfile(path):
            # a queued job has no telemetry yet: an empty tail at
            # offset 0 lets consumers poll one loop from submit on
            return self._send(204, b"", "application/x-ndjson",
                              {"X-Telemetry-Offset": "0"})
        data, next_off, rotated = tail_jsonl(path, offset)
        headers = {"X-Telemetry-Offset": str(next_off),
                   "X-Telemetry-Records":
                       str(data.count(b"\n"))}
        if rotated:
            headers["X-Telemetry-Rotated"] = "1"
        self._send(200, data, "application/x-ndjson", headers)

    def _file(self, obs: ObsServer, job_id: str, rel: str):
        path = obs.artifact_file(job_id, rel)
        if path is None:
            return self._error(404, f"no artifact {rel!r}")
        size = os.path.getsize(path)
        start, end = 0, size - 1
        status = 200
        rng = self.headers.get("Range", "")
        m = re.match(r"bytes=(\d*)-(\d*)$", rng) if rng else None
        if m and (m.group(1) or m.group(2)):
            if m.group(1):
                start = int(m.group(1))
                end = int(m.group(2)) if m.group(2) else size - 1
            else:               # suffix range: last N bytes
                start = max(0, size - int(m.group(2)))
            end = min(end, size - 1)
            if start > end or start >= size:
                return self._error(416, "unsatisfiable range")
            status = 206
        with open(path, "rb") as f:
            f.seek(start)
            body = f.read(end - start + 1)
        headers = {"Accept-Ranges": "bytes"}
        if status == 206:
            headers["Content-Range"] = f"bytes {start}-{end}/{size}"
        self._send(status, body, "application/octet-stream", headers)

    def _profile(self, obs: ObsServer, job_id: str,
                 query: Dict[str, str]):
        length = int(self.headers.get("Content-Length") or 0)
        req: Dict[str, Any] = {}
        if length:
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except ValueError:
                return self._error(400, "body must be JSON")
        if "chunks" in query:
            req["chunks"] = query["chunks"]
        try:
            req["chunks"] = int(req.get("chunks", 1))
        except (TypeError, ValueError):
            return self._error(400, "chunks must be an integer")
        self._json(obs.arm_profile(job_id, req), code=202)
