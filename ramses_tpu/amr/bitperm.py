"""Flat↔dense conversion for COMPLETE levels as a bit-permutation
reshape/transpose — no gather.

A complete level's flat row order is (sorted-Morton oct index) × (cell
offset): the sorted Morton keys of a full oct grid are simply
0..noct-1, so the flat cell index is a fixed *bit permutation* of the
dense C-order ravel index::

    flat bits (MSB→LSB):  [z_{l-1} y_{l-1} x_{l-1}] … [z_1 y_1 x_1] [x_0 y_0 z_0]
    dense bits (MSB→LSB): [x_{l-1} … x_0] [y_{l-1} … y_0] [z_{l-1} … z_0]

(x_k = bit k of the cell's x coordinate; the oct Morton triplets carry
coordinate bits 1..l-1 with z most significant per triplet —
``amr/keys.py`` ``encode`` — and the within-oct offset carries bit 0
with x slowest — ``amr/tree.py`` ``cell_offsets``.)

A gather by this permutation moves one ~nvar-float row per index: on
TPU that lowers to millions of latency-bound small copies and was the
dominant cost of the steady-state AMR step (BENCH_CAPTURED_r04).  A
reshape to ``(2,)*ndim*lvl`` axes + transpose expresses the same data
movement with static regular strides that XLA vectorizes.

Only valid for cubic complete levels (2^lvl cells per dim); callers
fall back to the index-permutation gather otherwise (non-cubic roots).
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp


@lru_cache(maxsize=None)
def _bit_axes(lvl: int, ndim: int) -> tuple:
    """Transpose permutation taking flat bit-axis order to dense
    (coordinate-major) bit-axis order.  Axis p of the reshaped flat
    array holds the p-th most significant flat index bit."""
    pos = {}
    p = 0
    for i in range(lvl - 1, 0, -1):           # oct Morton triplets
        for d in range(ndim - 1, -1, -1):     # z most significant
            pos[(d, i)] = p
            p += 1
    for d in range(ndim):                     # within-oct: x slowest
        pos[(d, 0)] = p
        p += 1
    return tuple(pos[(d, i)] for d in range(ndim)
                 for i in range(lvl - 1, -1, -1))


@lru_cache(maxsize=None)
def _inv_bit_axes(lvl: int, ndim: int) -> tuple:
    fwd = _bit_axes(lvl, ndim)
    inv = [0] * len(fwd)
    for i, a in enumerate(fwd):
        inv[a] = i
    return tuple(inv)


def flat_to_dense(rows, lvl: int, ndim: int):
    """[ncell(+pad), *trailing] flat-order rows → dense
    ``(2^lvl,)*ndim + trailing`` array (pure reshape/transpose)."""
    n = 1 << lvl
    ncell = n ** ndim
    trailing = rows.shape[1:]
    nb = ndim * lvl
    x = rows[:ncell].reshape((2,) * nb + trailing)
    ax = _bit_axes(lvl, ndim) + tuple(range(nb, nb + len(trailing)))
    return jnp.transpose(x, ax).reshape((n,) * ndim + trailing)


def dense_to_flat(dense, lvl: int, ndim: int):
    """Dense ``(2^lvl,)*ndim + trailing`` array → [ncell, *trailing]
    flat-order rows (inverse of :func:`flat_to_dense`)."""
    n = 1 << lvl
    ncell = n ** ndim
    trailing = dense.shape[ndim:]
    nb = ndim * lvl
    x = dense.reshape((2,) * nb + trailing)
    ax = _inv_bit_axes(lvl, ndim) + tuple(range(nb, nb + len(trailing)))
    return jnp.transpose(x, ax).reshape((ncell,) + trailing)
