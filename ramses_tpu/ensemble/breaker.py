"""Poison-config circuit breaker for the run-service queue.

Member quarantine (the isolation ladder) contains bad *sweep points*
inside a job; nothing contains a job whose frozen config kills the
worker *process* — every worker in the fleet burns an attempt on it,
the job bounces with backoff, and the fleet spends its life
crash-looping one namelist.  The breaker closes that hole with the
classic pattern: failures are counted per **frozen-config
fingerprint** (namelist text + sweeps + solver + ndim + dtype + kind),
and after N failures at the same normalized stage (``"crash"`` vs
``"hang"``) across at least ``min_workers`` distinct workers, the
breaker **trips**: matching queued jobs are parked (``parked/`` state
dir) with the breaker verdict appended to their ``failure_log``, and
no worker claims them.

State machine per fingerprint, stored as
``<queue_dir>/breakers/<fp>.json``:

* ``closed`` — counting; trips at the threshold.
* ``open`` — matching jobs are parked on sight.  After ``ttl_s`` the
  sweeper **half-opens** it.
* ``half_open`` — exactly one parked probe job is released back to
  ``queued/``.  If the probe fails, the breaker snaps back open (fresh
  TTL); if any matching job completes, the breaker closes and all
  remaining parked twins are released.

Operator override: ``tools/queue_fsck.py --reset-breaker <fp|all>``
half-opens immediately.  Knobs (worker-side env):
``RAMSES_BREAKER_N`` (failure threshold, default 3, ``0`` disables),
``RAMSES_BREAKER_MIN_WORKERS`` (default 2 — a single flaky host can't
trip it alone), ``RAMSES_BREAKER_TTL_S`` (default 3600).

Everything is stdlib + the jax-free queue module; state writes go
through the queue's tmp+fsync+replace so a torn breaker file can't
exist (and fsck sweeps the tmps if the process dies mid-write).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

from ramses_tpu.ensemble import queue as jq

BREAKERS_DIR = "breakers"

DEFAULT_FAILURES = 3
DEFAULT_MIN_WORKERS = 2
DEFAULT_TTL_S = 3600.0

#: gauge encoding shared with obs/metrics: closed=0 half_open=1 open=2
STATE_VALUE = {"closed": 0, "half_open": 1, "open": 2}


def _env_num(name: str, default, cast):
    try:
        raw = os.environ.get(name)
        return cast(raw) if raw not in (None, "") else default
    except (TypeError, ValueError):
        return default


def _knobs(failures=None, min_workers=None, ttl_s=None):
    if failures is None:
        failures = _env_num("RAMSES_BREAKER_N", DEFAULT_FAILURES, int)
    if min_workers is None:
        min_workers = _env_num("RAMSES_BREAKER_MIN_WORKERS",
                               DEFAULT_MIN_WORKERS, int)
    if ttl_s is None:
        ttl_s = _env_num("RAMSES_BREAKER_TTL_S", DEFAULT_TTL_S, float)
    return int(failures), max(1, int(min_workers)), float(ttl_s)


def config_fingerprint(record: Dict[str, Any]) -> str:
    """Stable fingerprint of everything that makes two jobs the *same
    run configuration*: namelist text, explicit sweeps, solver, ndim,
    dtype, kind.  Worker identity, attempts, ids and timestamps are
    deliberately excluded — the breaker asks "is this CONFIG poison",
    not "is this job unlucky"."""
    h = hashlib.sha256()
    for part in (str(record.get("namelist", "")),
                 json.dumps(record.get("sweeps") or {}, sort_keys=True),
                 str(record.get("solver", "")),
                 str(int(record.get("ndim", 3) or 3)),
                 str(record.get("dtype", "")),
                 jq.job_kind(record)):
        h.update(part.encode())
        h.update(b"\0")
    return h.hexdigest()[:16]


def fingerprint_of(record: Dict[str, Any]) -> str:
    """The record's stamped fingerprint (submit-time) or a recomputed
    one for records that predate the field."""
    return str(record.get("config_fp") or config_fingerprint(record))


def breaker_stage(stage: str) -> str:
    """Normalize failure_log stages to the breaker's two failure
    classes: the serve loop labels hang-kills ``"hang"`` and
    everything else (``requeue``/``fail``/exceptions) is a crash.
    Counting on the raw disposition would never accumulate — a job's
    first failures are ``requeue`` and its last is ``fail``."""
    return "hang" if stage == "hang" else "crash"


def _path(queue_dir: str, fp: str) -> str:
    return os.path.join(queue_dir, BREAKERS_DIR, fp + ".json")


def load(queue_dir: str, fp: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_path(queue_dir, fp)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _store(queue_dir: str, b: Dict[str, Any]) -> None:
    os.makedirs(os.path.join(queue_dir, BREAKERS_DIR), exist_ok=True)
    jq._write_record(_path(queue_dir, b["fp"]), b)


def list_breakers(queue_dir: str) -> List[Dict[str, Any]]:
    d = os.path.join(queue_dir, BREAKERS_DIR)
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(n for n in os.listdir(d) if n.endswith(".json"))
    except OSError:
        return out
    for name in names:
        try:
            with open(os.path.join(d, name)) as f:
                out.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return out


def open_fingerprints(queue_dir: str) -> Dict[str, str]:
    """``{fp: verdict}`` for every breaker currently open — the serve
    loop's pre-claim parking filter (one directory read per poll, not
    one per record)."""
    return {str(b.get("fp", "")): str(b.get("verdict", "breaker open"))
            for b in list_breakers(queue_dir)
            if b.get("state") == "open"}


def record_failure(queue_dir: str, record: Dict[str, Any], stage: str,
                   failures: Optional[int] = None,
                   min_workers: Optional[int] = None,
                   ttl_s: Optional[float] = None,
                   telemetry=None, log=None) -> bool:
    """Count one worker-attributable failure against the record's
    config fingerprint; trip the breaker (and park matching queued
    jobs) when the cross-worker threshold is crossed.  A failure while
    half-open snaps the breaker back to open — the probe failed.
    Returns True when this call tripped/re-tripped the breaker."""
    n_trip, min_w, ttl = _knobs(failures, min_workers, ttl_s)
    if n_trip <= 0:
        return False                   # breaker disabled
    fp = fingerprint_of(record)
    now = time.time()
    b = load(queue_dir, fp) or {
        "fp": fp, "state": "closed", "failures": [],
        "kind": jq.job_kind(record)}
    stage_b = breaker_stage(stage)
    b.setdefault("failures", []).append({
        "stage": stage_b, "worker": str(record.get("worker", "")),
        "job": str(record.get("id", "")), "time_unix": now})
    b["failures"] = b["failures"][-50:]
    tripped = False
    if b.get("state") == "half_open":
        # the released probe failed: no counting debate, snap open
        tripped = True
        _trip(queue_dir, b, stage_b, ttl, now,
              verdict=(f"half-open probe failed again at stage "
                       f"'{stage_b}' (job {record.get('id', '?')})"),
              telemetry=telemetry, log=log)
    elif b.get("state") == "closed":
        same = [f for f in b["failures"] if f.get("stage") == stage_b]
        workers = {f.get("worker") for f in same if f.get("worker")}
        if len(same) >= n_trip and len(workers) >= min_w:
            tripped = True
            _trip(queue_dir, b, stage_b, ttl, now,
                  verdict=(f"{len(same)} '{stage_b}' failures across "
                           f"{len(workers)} worker(s) on config "
                           f"{fp}"),
                  telemetry=telemetry, log=log)
    _store(queue_dir, b)
    return tripped


def _trip(queue_dir: str, b: Dict[str, Any], stage: str, ttl_s: float,
          now: float, verdict: str, telemetry=None, log=None) -> None:
    b["state"] = "open"
    b["stage"] = stage
    b["tripped_unix"] = now
    b["ttl_s"] = float(ttl_s)
    b["verdict"] = f"circuit breaker open: {verdict}"
    if log is not None:
        log(f"breaker: OPEN {b['fp']} — {verdict}")
    if telemetry is not None:
        try:
            telemetry.record_event("breaker_trip", fp=b["fp"],
                                   stage=stage, verdict=b["verdict"])
        except Exception:
            pass
    park_matching(queue_dir, b["fp"], b["verdict"],
                  telemetry=telemetry, log=log)


def park_record(queue_dir: str, record: Dict[str, Any], verdict: str,
                telemetry=None, log=None) -> bool:
    """Move one queued record to ``parked/`` with the breaker verdict
    in its failure_log.  Tolerates losing the record to a racing
    claim (returns False)."""
    job_id = str(record.get("id", ""))
    src = os.path.join(queue_dir, "queued", job_id + ".json")
    try:
        with open(src) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    rec.setdefault("failure_log", []).append({
        "error": verdict, "stage": "breaker", "kind": jq.job_kind(rec),
        "attempt": int(rec.get("attempts", 0)), "worker": "",
        "trace_id": rec.get("trace_id", ""), "time_unix": time.time()})
    rec["parked_by"] = fingerprint_of(rec)
    dst = os.path.join(queue_dir, "parked", job_id + ".json")
    try:
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        jq._write_record(src, rec)
        os.rename(src, dst)
    except OSError:
        return False
    if log is not None:
        log(f"breaker: parked {job_id} ({verdict})")
    if telemetry is not None:
        try:
            telemetry.record_event("breaker_park", job=job_id,
                                   fp=rec.get("parked_by", ""),
                                   trace_id=rec.get("trace_id", ""))
        except Exception:
            pass
    return True


def park_matching(queue_dir: str, fp: str, verdict: str,
                  telemetry=None, log=None) -> int:
    n = 0
    for rec in jq.peek_queued(queue_dir):
        if fingerprint_of(rec) == fp:
            n += int(park_record(queue_dir, rec, verdict,
                                 telemetry=telemetry, log=log))
    return n


def _parked_matching(queue_dir: str, fp: str) -> List[str]:
    d = os.path.join(queue_dir, "parked")
    out: List[str] = []
    try:
        names = sorted(n for n in os.listdir(d) if n.endswith(".json"))
    except OSError:
        return out
    for name in names:
        try:
            with open(os.path.join(d, name)) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if fingerprint_of(rec) == fp:
            out.append(str(rec.get("id", name[:-len(".json")])))
    return out


def half_open(queue_dir: str, fp: str,
              b: Optional[Dict[str, Any]] = None,
              telemetry=None, log=None) -> bool:
    """open -> half_open: release exactly one parked probe job back to
    the queue; the rest stay parked until the probe's verdict."""
    b = b if b is not None else load(queue_dir, fp)
    if b is None or b.get("state") != "open":
        return False
    b["state"] = "half_open"
    b["half_open_unix"] = time.time()
    _store(queue_dir, b)
    probe = None
    for job_id in _parked_matching(queue_dir, fp):
        if jq.unpark(queue_dir, job_id,
                     note=f"breaker {fp} half-open probe"):
            probe = job_id
            break
    if log is not None:
        log(f"breaker: HALF-OPEN {fp}"
            + (f" — probe {probe} released" if probe else ""))
    if telemetry is not None:
        try:
            telemetry.record_event("breaker_half_open", fp=fp,
                                   probe=probe or "")
        except Exception:
            pass
    return True


def on_success(queue_dir: str, record: Dict[str, Any],
               telemetry=None, log=None) -> bool:
    """A matching job completed: close the breaker (whatever its
    state) and release every parked twin."""
    fp = fingerprint_of(record)
    b = load(queue_dir, fp)
    if b is None or b.get("state") == "closed":
        return False
    b["state"] = "closed"
    b["failures"] = []
    b["closed_unix"] = time.time()
    _store(queue_dir, b)
    released = 0
    for job_id in _parked_matching(queue_dir, fp):
        released += int(jq.unpark(queue_dir, job_id,
                                  note=f"breaker {fp} closed"))
    if log is not None:
        log(f"breaker: CLOSED {fp} — {released} parked job(s) released")
    if telemetry is not None:
        try:
            telemetry.record_event("breaker_close", fp=fp,
                                   released=released)
        except Exception:
            pass
    return True


def sweep(queue_dir: str, ttl_s: Optional[float] = None,
          telemetry=None, log=None) -> int:
    """TTL maintenance, called from the serve poll loop: every open
    breaker whose TTL expired is half-opened (one probe released).
    Returns the number of transitions."""
    now = time.time()
    n = 0
    for b in list_breakers(queue_dir):
        if b.get("state") != "open":
            continue
        ttl = float(b.get("ttl_s", DEFAULT_TTL_S)
                    if ttl_s is None else ttl_s)
        if now >= float(b.get("tripped_unix", now)) + ttl:
            n += int(half_open(queue_dir, str(b.get("fp", "")), b=b,
                               telemetry=telemetry, log=log))
    return n


def reset(queue_dir: str, fp: str = "all", log=print) -> List[str]:
    """Operator reset (``queue_fsck --reset-breaker``): half-open the
    named breaker, or every open one with ``"all"``.  Returns the
    fingerprints transitioned."""
    done: List[str] = []
    for b in list_breakers(queue_dir):
        bfp = str(b.get("fp", ""))
        if fp not in ("all", bfp):
            continue
        if b.get("state") == "open" and half_open(queue_dir, bfp, b=b,
                                                  log=log):
            done.append(bfp)
    return done
