"""Cost-weighted Hilbert load balancing for the sharded AMR path.

The reference's ``load_balance.f90`` (``cost_weighting``) assigns each oct
a cost — solver sweeps plus particle work — and cuts the Hilbert curve
into per-CPU segments of near-equal summed cost.  Here the analog: each
partial level's dense row batch is a padded ``[noct_pad, ...]`` block
row-sharded over the 1-D "oct" mesh axis, device ``d`` owning rows
``[d*cap, (d+1)*cap)`` with ``cap = noct_pad // ndev``.  The seed layout
was the identity (tree/Morton order, trailing pads) — blind equal row
splits.  A :class:`LevelLayout` generalizes this to an arbitrary
permutation: device ``d``'s row segment holds a *contiguous Hilbert-key
range* of ``n_d <= cap`` real octs (pads fill the remainder of each
segment), with the ``n_d`` chosen by a capacity-constrained weighted cut
so per-device summed cost is balanced within the bucket-padding bound.

Layouts are applied *after* the tree-order map builders
(`amr/maps.py`) as a pure index transform — ``apply_layout_level`` /
``apply_layout_gravity`` permute oct/cell rows and remap stored row
values.  Because `parallel/amr_comm.py` derives ownership purely from
``row // rows_per_device``, halo schedules built from transformed maps
are automatically correct against the new cuts — no comm-layer changes.

Complete levels always keep the identity layout: their dense bit-permute
sweep path depends on lexicographic row order.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from ramses_tpu.amr.hilbert import hilbert_order

__all__ = [
    "LevelLayout", "BalanceStats", "oct_costs", "balanced_cuts",
    "make_layout", "compute_layouts", "measure", "enabled",
    "apply_layout_level", "apply_layout_blocks", "apply_layout_gravity",
    "remap_son_oct",
    "remap_octs", "remap_cells", "layout_sig", "layouts_same",
    "merge_ranges", "ranges_cover",
]


@dataclass(frozen=True)
class LevelLayout:
    """Row placement of one partial level's ``noct`` real octs inside its
    padded ``noct_pad`` batch, split over ``ndev`` equal row segments.

    ``oct_row[i]`` is the row slot of tree oct ``i``; ``row_oct[r]`` the
    inverse (-1 on pad rows).  Real rows are NOT contiguous — each device
    segment carries its own trailing pads — so consumers must gather
    through ``oct_row`` instead of slicing ``[:noct]``.
    """
    noct: int
    noct_pad: int
    ndev: int
    oct_row: np.ndarray      # [noct] int64, tree oct idx -> row slot
    row_oct: np.ndarray      # [noct_pad] int64, row slot -> oct idx | -1
    counts: np.ndarray       # [ndev] int64 real octs per device segment
    sig: int                 # value hash for cache keys / reuse checks


@dataclass(frozen=True)
class BalanceStats:
    """Per-device summed cost under the current layouts."""
    per_dev: np.ndarray      # [ndev] float64
    max_cost: float
    mean_cost: float
    imbalance: float         # max/mean, 1.0 when perfectly balanced

    def __str__(self):
        return (f"max/mean={self.max_cost:.4g}/{self.mean_cost:.4g} "
                f"imb={self.imbalance:.3f}")


def layout_sig(lay: Optional[LevelLayout]) -> Optional[int]:
    return None if lay is None else lay.sig


def layouts_same(a: Dict[int, LevelLayout], b: Dict[int, LevelLayout],
                 levels=None) -> bool:
    keys = (set(a) | set(b)) if levels is None else set(levels)
    return all(layout_sig(a.get(l)) == layout_sig(b.get(l)) for l in keys)


def merge_ranges(ranges) -> list:
    """Coalesce ``[start, length]`` (or ``(start, length)``) row
    intervals into a sorted list of maximal disjoint ``[start, end)``
    pairs.  Empty/zero-length intervals are dropped."""
    ivs = sorted((int(r0), int(r0) + int(n)) for r0, n in ranges
                 if int(n) > 0)
    out: list = []
    for lo, hi in ivs:
        if out and lo <= out[-1][1]:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return out


def ranges_cover(ranges, total: int):
    """Whether ``[start, length]`` intervals cover ``[0, total)`` with
    no gap.  Returns ``(covered, first_gap)`` — ``first_gap`` is the
    ``[lo, hi)`` of the first uncovered span (None when covered).
    Elastic restore uses this to decide whether a surviving shard
    subset still reconstructs every row of the saved hierarchy."""
    total = int(total)
    if total <= 0:
        return True, None
    merged = merge_ranges(ranges)
    pos = 0
    for lo, hi in merged:
        if lo > pos:
            return False, [pos, lo]
        pos = max(pos, hi)
        if pos >= total:
            return True, None
    return pos >= total, (None if pos >= total else [pos, total])


# ---------------------------------------------------------------- cost model

def oct_costs(sim, l: int) -> np.ndarray:
    """Per-oct cost [noct] at level ``l`` — the ``cost_weighting`` analog.

    Base term: cells per oct times a solver weight (MHD/RT sweeps cost
    more than plain hydro) times the subcycle factor ``2^(l-lmin)`` (a
    level-``l`` oct is swept that many times per coarse step).  Particle
    term: per-oct particle counts times ``cost_weight_part``.
    """
    amr = sim.params.amr
    tree = sim.tree
    noct = tree.noct(l)
    ttd = 1 << tree.ndim
    physics = getattr(sim.cfg, "physics", "hydro")
    if physics == "mhd":
        w_solver = float(getattr(amr, "cost_weight_mhd", 2.0))
    else:
        w_solver = float(getattr(amr, "cost_weight_hydro", 1.0))
    if getattr(sim, "rt_amr", None) is not None:
        w_solver += float(getattr(amr, "cost_weight_rt", 1.5))
    sub = float(1 << (l - sim.lmin))
    w = np.full(noct, w_solver * ttd * sub, dtype=np.float64)

    p = getattr(sim, "p", None)
    w_part = float(getattr(amr, "cost_weight_part", 0.3))
    if p is not None and w_part > 0.0:
        x = np.asarray(p.x, dtype=np.float64)[:, :tree.ndim]
        act = np.asarray(p.active, dtype=bool)
        if act.any():
            x = x[act]
            boxlen = float(amr.boxlen)
            dx_oct = boxlen / (1 << (l - 1))   # oct size, assign_levels conv
            og = np.floor(x / dx_oct).astype(np.int64)
            og = np.clip(og, 0, (1 << (l - 1)) - 1)
            idx = tree.lookup(l, og)
            idx = idx[idx >= 0]
            if len(idx):
                w += w_part * np.bincount(idx, minlength=noct)[:noct]
    return w


# ------------------------------------------------------------ weighted cuts

def balanced_cuts(w: np.ndarray, ndev: int, cap: int) -> np.ndarray:
    """Split ``w`` (costs in curve order) into ``ndev`` contiguous runs of
    at most ``cap`` items each, greedily equalizing summed cost.

    Returns per-device counts summing to ``len(w)``.  Feasibility
    (``len(w) <= ndev*cap``) is the caller's padding invariant; the
    per-segment clamp ``end >= n - remaining*cap`` keeps every later
    device within capacity.
    """
    n = len(w)
    if n > ndev * cap:
        raise ValueError(f"infeasible cut: {n} octs > {ndev}x{cap}")
    cw = np.concatenate([[0.0], np.cumsum(np.asarray(w, dtype=np.float64))])
    total = cw[-1]
    counts = np.zeros(ndev, dtype=np.int64)
    start = 0
    for d in range(ndev):
        rem = ndev - d
        if d == ndev - 1:
            end = n
        else:
            lo = max(start, n - (rem - 1) * cap)
            hi = min(start + cap, n)
            target = cw[start] + (total - cw[start]) / rem
            end = int(np.searchsorted(cw, target, side="left"))
            # the cut just below may sit closer to the target
            if end - 1 >= start and end <= n and \
                    target - cw[end - 1] <= cw[min(end, n)] - target:
                end -= 1
            end = min(max(end, lo), hi)
        counts[d] = end - start
        start = end
    assert start == n
    return counts


def make_layout(order: np.ndarray, counts: np.ndarray, noct_pad: int,
                ndev: int) -> LevelLayout:
    """Layout placing curve-order octs ``order`` into per-device segments
    of ``counts`` real rows each (pads trail inside every segment)."""
    noct = len(order)
    cap = noct_pad // ndev
    oct_row = np.empty(noct, dtype=np.int64)
    row_oct = np.full(noct_pad, -1, dtype=np.int64)
    start = 0
    for d in range(ndev):
        c = int(counts[d])
        seg = order[start:start + c]
        rows = d * cap + np.arange(c, dtype=np.int64)
        oct_row[seg] = rows
        row_oct[rows] = seg
        start += c
    sig = hash((noct, noct_pad, ndev, oct_row.tobytes()))
    return LevelLayout(noct=noct, noct_pad=noct_pad, ndev=ndev,
                       oct_row=oct_row, row_oct=row_oct,
                       counts=np.asarray(counts, dtype=np.int64), sig=sig)


def _is_identity(lay: LevelLayout) -> bool:
    return bool(np.array_equal(lay.oct_row, np.arange(lay.noct)))


def compute_layouts(sim) -> Dict[int, LevelLayout]:
    """Candidate layouts for every partial level of ``sim.tree`` —
    cost-weighted cuts along the Hilbert curve (``run.ordering``
    'hilbert'; tree/Morton order otherwise).  Identity results are
    dropped so absent == identity holds everywhere."""
    tree = sim.tree
    ndev = int(getattr(sim, "ndev", 1))
    hilbert = getattr(sim.params.run, "ordering", "hilbert") == "hilbert"
    out: Dict[int, LevelLayout] = {}
    for l in sim.levels():
        noct = tree.noct(l)
        if noct == int(np.prod(tree.oct_dims(l))):
            continue                       # complete level: keep identity
        noct_pad = sim._noct_pad(l, noct)
        cap = noct_pad // ndev
        if hilbert:
            og = tree.levels[l].og
            nbits = max(1, int(np.max(og)).bit_length())
            order = hilbert_order(og, tree.ndim, nbits)
        else:
            order = np.arange(noct, dtype=np.int64)
        w = oct_costs(sim, l)
        counts = balanced_cuts(w[order], ndev, cap)
        lay = make_layout(order, counts, noct_pad, ndev)
        if not _is_identity(lay):
            out[l] = lay
    return out


def measure(sim, layouts: Optional[Dict[int, LevelLayout]] = None
            ) -> BalanceStats:
    """Aggregate per-device cost over all levels under ``layouts``
    (default: the sim's current layouts; absent level == identity)."""
    if layouts is None:
        layouts = getattr(sim, "layouts", {})
    ndev = int(getattr(sim, "ndev", 1))
    per = np.zeros(ndev, dtype=np.float64)
    for l in sim.levels():
        noct = sim.tree.noct(l)
        w = oct_costs(sim, l)
        lay = layouts.get(l)
        cap = (lay.noct_pad if lay is not None
               else sim._noct_pad(l, noct)) // ndev
        rows = lay.oct_row if lay is not None \
            else np.arange(noct, dtype=np.int64)
        per += np.bincount(rows // cap, weights=w, minlength=ndev)[:ndev]
    mean = float(per.sum()) / ndev
    mx = float(per.max()) if len(per) else 0.0
    imb = mx / mean if mean > 0 else 1.0
    return BalanceStats(per_dev=per, max_cost=mx, mean_cost=mean,
                        imbalance=imb)


def enabled(sim) -> bool:
    """Opt-in gate: ``&AMR_PARAMS load_balance`` plus the reference's
    ``cost_weighting`` run flag, restricted to the state layers the
    layout transform covers (hydro + gravity + PM particles).  Layers
    carrying extra per-cell/side-channel state keep the identity layout."""
    p = sim.params
    if not bool(getattr(p.amr, "load_balance", False)):
        return False
    if not bool(getattr(p.run, "cost_weighting", True)):
        return False
    if getattr(sim.cfg, "physics", "hydro") != "hydro":
        return False                      # MHD face fields / SR state
    if getattr(sim, "_needs_mig_log", False):
        return False                      # subclass-owned per-cell state
    if getattr(sim, "rt_amr", None) is not None:
        return False
    if getattr(sim, "tracer_x", None) is not None:
        return False
    if getattr(sim, "sinks", None) is not None:
        return False
    if getattr(sim, "movie", None) is not None:
        return False
    sf = getattr(sim, "sf_spec", None)
    if sf is not None and getattr(sf, "enabled", False):
        return False
    return True


# ------------------------------------------------------- layout application
#
# Value-remap conventions (ttd = 2^ndim):
#   oct value v at level L      ->  oct_row_L[v]           (v < noct)
#   flat cell value v at L      ->  oct_row_L[v//ttd]*ttd + v%ttd
# Sentinels (trash rows, ghost slots, -1, noct_pad) pass through unchanged.
# Row permutation of an oct-indexed [noct_pad, ...] array scatters the
# first ``noct`` rows to ``oct_row`` slots and fills pads.

def remap_octs(v: np.ndarray, lay: LevelLayout) -> np.ndarray:
    """Remap oct-index values through ``lay``; anything outside
    ``[0, noct)`` (sentinels like ``noct_pad``, -1) passes through."""
    v64 = np.asarray(v).astype(np.int64)
    mapped = lay.oct_row[np.clip(v64, 0, lay.noct - 1)]
    return np.where((v64 >= 0) & (v64 < lay.noct), mapped,
                    v64).astype(np.asarray(v).dtype)


def remap_cells(v: np.ndarray, lay: LevelLayout, ttd: int) -> np.ndarray:
    """Remap flat-cell values through ``lay``; anything outside
    ``[0, noct*ttd)`` (pad cells, ghost slots, trash rows, the PM
    ``ncell_pad`` sentinel, -1) passes through."""
    v64 = np.asarray(v).astype(np.int64)
    ncell = lay.noct * ttd
    mapped = (lay.oct_row[np.clip(v64, 0, ncell - 1) // ttd] * ttd
              + np.where(v64 >= 0, v64 % ttd, 0))
    return np.where((v64 >= 0) & (v64 < ncell), mapped,
                    v64).astype(np.asarray(v).dtype)


def _perm_oct_rows(a: np.ndarray, lay: LevelLayout, fill) -> np.ndarray:
    out = np.full_like(a, fill)
    out[lay.oct_row] = a[:lay.noct]
    return out


def _perm_cell_rows(a: np.ndarray, lay: LevelLayout, ttd: int,
                    fill) -> np.ndarray:
    rows = (lay.oct_row[:, None] * ttd
            + np.arange(ttd, dtype=np.int64)).reshape(-1)
    out = np.full_like(a, fill)
    out[rows] = a[:lay.noct * ttd]
    return out


def remap_son_oct(m, lay_p1: LevelLayout):
    """Remap ``son_oct`` values (oct indices at l+1) through the l+1
    layout.  Pad entries hold 0 and land on ``oct_row[0]`` — harmless,
    their ``ref_cell`` is -1."""
    from dataclasses import replace
    return replace(m, son_oct=remap_octs(m.son_oct, lay_p1))


def apply_layout_level(m, lay_m1: Optional[LevelLayout],
                       lay: Optional[LevelLayout],
                       lay_p1: Optional[LevelLayout]):
    """Transform tree-order ``LevelMaps`` into layout order.

    Rows of oct-indexed arrays are permuted by ``lay``; stored index
    values are remapped through the layout of the level they point at
    (cells of l: ``lay``; cells of l-1: ``lay_m1``; octs of l+1:
    ``lay_p1``)."""
    from dataclasses import replace
    if m.complete:
        assert lay is None and lay_m1 is None, \
            "complete levels keep the identity layout"
        return remap_son_oct(m, lay_p1) if lay_p1 is not None else m

    ttd = 1 << m.ndim
    kw = {}
    if lay is not None:
        assert lay.noct == m.noct and lay.noct_pad == m.noct_pad, \
            f"layout/maps mismatch at lvl {m.lvl}"
        trash = m.ncell_pad + m.ni_pad
        # stencil values: cells of l (< ncell_pad) remap; interp slots
        # (>= ncell_pad) and the trash row pass through remap_cells
        src = remap_cells(m.stencil_src, lay, ttd)
        kw["stencil_src"] = _perm_oct_rows(src, lay, trash)
        if m.vsgn is not None:
            kw["vsgn"] = _perm_oct_rows(m.vsgn, lay, 0)
        kw["ok_ref"] = _perm_oct_rows(m.ok_ref, lay, False)
        kw["valid_oct"] = _perm_oct_rows(m.valid_oct, lay, False)
        corr = _perm_oct_rows(m.corr_idx, lay, -1)
        kw["ref_cell"] = remap_cells(m.ref_cell, lay, ttd)
    else:
        corr = m.corr_idx
        kw["ref_cell"] = m.ref_cell
    if lay_m1 is not None:
        kw["interp_cell"] = remap_cells(m.interp_cell, lay_m1, ttd)
        kw["interp_nb"] = remap_cells(m.interp_nb, lay_m1, ttd)
        corr = remap_cells(corr, lay_m1, ttd)
    kw["corr_idx"] = corr
    son = m.son_oct
    if lay_p1 is not None:
        son = remap_octs(son, lay_p1)
    kw["son_oct"] = son
    return replace(m, **kw)


def apply_layout_blocks(b, lay_m1: Optional[LevelLayout],
                        lay: Optional[LevelLayout]):
    """Transform tree-order ``BlockMaps`` into layout order.

    Tile-indexed arrays (``tile_src``/``tile_ok``/``tile_vsgn`` rows and
    the incremental-rebuild geometry) keep tree/Morton row order — tiles
    are a pure function of the Morton prefix set, independent of where
    the layout placed each oct's flat row.  Only the *values* that point
    at flat cell rows remap: ``tile_src`` entries (cells of l; interp
    slots and the trash row pass through), ``interp_cell``/``interp_nb``
    (cells of l-1), and the scatter-back maps ``cell_tile``/``cell_slot``
    / ``oct_tile``/``oct_slot``, whose ROWS are flat-cell/oct rows and so
    permute with the layout.  Pad rows keep the zero-output sentinels
    (``cell_slot = c^ndim`` gathers the appended zero column; pad-oct
    corr garbage is dropped by the layout-transformed ``corr_idx = -1``).
    """
    from dataclasses import replace
    if lay is None and lay_m1 is None:
        return b
    ttd = 1 << b.ndim
    kw = {}
    if lay is not None:
        assert lay.noct == b.noct and lay.noct_pad == b.noct_pad, \
            f"layout/blocks mismatch at lvl {b.lvl}"
        c = 1 << (b.shift + 1)
        kw["tile_src"] = remap_cells(b.tile_src, lay, ttd)
        kw["cell_tile"] = _perm_cell_rows(b.cell_tile, lay, ttd, 0)
        kw["cell_slot"] = _perm_cell_rows(b.cell_slot, lay, ttd,
                                          c ** b.ndim)
        kw["oct_tile"] = _perm_oct_rows(b.oct_tile, lay, 0)
        kw["oct_slot"] = _perm_oct_rows(b.oct_slot, lay, 0)
    if lay_m1 is not None:
        kw["interp_cell"] = remap_cells(b.interp_cell, lay_m1, ttd)
        kw["interp_nb"] = remap_cells(b.interp_nb, lay_m1, ttd)
    return replace(b, **kw)


def apply_layout_gravity(g, lay_m1: Optional[LevelLayout],
                         lay: Optional[LevelLayout]):
    """Transform tree-order ``GravityMaps`` into layout order."""
    from dataclasses import replace
    if lay is None and lay_m1 is None:
        return g
    ndim = g.nb.shape[1]
    ttd = 1 << ndim
    kw = {}
    if lay is not None:
        # nb values index concat(cells, ghosts, zero): only cells
        # (< ncell_pad) remap; pad rows point at zero_row = ncell_pad+ng_pad
        zrow = g.ncell_pad + g.ng_pad
        kw["nb"] = _perm_cell_rows(remap_cells(g.nb, lay, ttd),
                                   lay, ttd, zrow)
        kw["valid_cell"] = _perm_cell_rows(g.valid_cell, lay, ttd, False)
        if g.oct_nb is not None:
            noct_pad = g.oct_nb.shape[0]
            kw["oct_nb"] = _perm_oct_rows(remap_octs(g.oct_nb, lay),
                                          lay, noct_pad)
        if g.mg:
            nb0, par0, n0 = g.mg[0]
            par0p = _perm_oct_rows(par0, lay, int(nb0.shape[0]))
            kw["mg"] = ((nb0, par0p, n0),) + tuple(g.mg[1:])
    if lay_m1 is not None:
        kw["g_cell"] = remap_cells(g.g_cell, lay_m1, ttd)
        kw["g_nb"] = remap_cells(g.g_nb, lay_m1, ttd)
    return replace(g, **kw)
