"""Source physics on the AMR hierarchy: in-step cooling, star
formation + SN feedback, sinks, tracer advection
(``amr/amr_step.f90:369-380,448-474,493,549-567`` ordering)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.amr.hierarchy import AmrSim

UNITS = {"units_density": 1.66e-24, "units_time": 3.15e13,
         "units_length": 3.08e18}


def _blob_groups(lmin=4, lmax=4, d_in=10.0, p_in=100.0, tend=0.01,
                 **extra):
    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmax, "boxlen": 1.0,
                       "npartmax": 10000},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [10.0, 0.25], "length_y": [10.0, 0.25],
                        "length_z": [10.0, 0.25],
                        "exp_region": [10.0, 2.0],
                        "d_region": [0.1, d_in],
                        "p_region": [0.05, p_in]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "units_params": dict(UNITS),
        "output_params": {"tend": tend},
    }
    g.update(extra)
    return g


def test_amr_cooling_matches_uniform():
    """Complete-level AMR cooling == the uniform run_steps_cool path."""
    from ramses_tpu.driver import Simulation

    g = _blob_groups(cooling_params={"cooling": True})
    sim = AmrSim(params_from_dict({k: dict(v) for k, v in g.items()},
                                  ndim=3), dtype=jnp.float64)
    assert sim.cool_tables is not None
    sim.evolve(0.01)
    e_amr = sim.totals()[4]

    us = Simulation(params_from_dict({k: dict(v) for k, v in g.items()},
                                     ndim=3), dtype=jnp.float64)
    us.evolve()
    e_uni = float(np.asarray(us.state.u)[4].sum()) * us.dx ** 3
    assert np.isclose(e_amr, e_uni, rtol=1e-12)


def test_amr_cooling_radiates():
    """Hot dense gas must lose energy vs the adiabatic run."""
    g = _blob_groups(d_in=100.0, p_in=10000.0, tend=0.02,
                     cooling_params={"cooling": True})
    cool = AmrSim(params_from_dict({k: dict(v) for k, v in g.items()},
                                   ndim=3), dtype=jnp.float64)
    cool.evolve(0.02, nstepmax=8)
    g2 = _blob_groups(d_in=100.0, p_in=10000.0, tend=0.02)
    adia = AmrSim(params_from_dict({k: dict(v) for k, v in g2.items()},
                                   ndim=3), dtype=jnp.float64)
    adia.evolve(0.02, nstepmax=8)
    assert cool.totals()[4] < adia.totals()[4] * (1 - 1e-6)


@pytest.mark.slow
def test_star_formation_on_hierarchy():
    """Stars form in the refined dense blob at its finest covering
    level; gas+stars mass is conserved; SN feedback fires once."""
    g = _blob_groups(lmin=4, lmax=6, d_in=50.0, p_in=0.5, tend=0.05,
                     refine_params={"err_grad_d": 0.2},
                     sf_params={"n_star": 1.0, "t_star": 0.1,
                                "m_star": 1.0},
                     feedback_params={"eta_sn": 0.1, "t_sne": 0.001})
    g["run_params"]["poisson"] = True
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    m0 = sim.totals()[0]
    sim.evolve(0.05, nstepmax=20)
    act = np.asarray(sim.p.active)
    nstars = int(act.sum())
    assert nstars > 0
    m_stars = float((np.asarray(sim.p.m) * act).sum())
    m1 = sim.totals()[0]
    assert abs(m1 + m_stars - m0) < 1e-11

    from ramses_tpu.pm.amr_pm import assign_levels
    lv = assign_levels(sim.tree, np.asarray(sim.p.x)[act], sim.boxlen)
    assert (lv > sim.lmin).all()          # blob is refined: stars too
    assert int((np.asarray(sim.p.flags) & 1).sum()) > 0   # SNe fired


@pytest.mark.slow
def test_sinks_on_hierarchy():
    """Threshold sinks form in the refined blob and accrete; gas+sink
    mass conserved."""
    g = _blob_groups(lmin=4, lmax=5, d_in=100.0, p_in=1.0, tend=0.02,
                     refine_params={"err_grad_d": 0.2},
                     sink_params={"create_sinks": True, "n_sink": 10.0,
                                  "accretion_scheme": "threshold",
                                  "c_acc": 0.1})
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    m0 = sim.totals()[0]
    sim.evolve(0.02, nstepmax=10)
    assert sim.sinks.n > 0
    ms = sim.sinks.m.sum()
    assert ms > 0
    m1 = sim.totals()[0]
    assert abs(m1 + ms - m0) < 1e-11


@pytest.mark.slow
def test_tracers_follow_gas_on_hierarchy():
    """Velocity tracers advect with the flow: a tracer in the expanding
    blast moves outward, all positions stay finite/periodic."""
    g = _blob_groups(lmin=4, lmax=5, d_in=1.0, p_in=100.0, tend=0.05,
                     refine_params={"err_grad_p": 0.2})
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    rng = np.random.default_rng(0)
    # ring of tracers just outside the hot blob: the blast pushes them out
    th = rng.uniform(0, 2 * np.pi, 64)
    r0 = 0.16
    x0 = np.stack([0.5 + r0 * np.cos(th), 0.5 + r0 * np.sin(th),
                   np.full(64, 0.5)], axis=1)
    sim.tracer_x = x0.copy()
    sim.evolve(0.05, nstepmax=15)
    r1 = np.sqrt(((sim.tracer_x[:, :2] - 0.5) ** 2).sum(axis=1))
    assert np.isfinite(sim.tracer_x).all()
    assert (sim.tracer_x >= 0).all() and (sim.tracer_x <= 1).all()
    assert r1.mean() > r0 + 1e-4          # net outward advection


@pytest.mark.slow
def test_stellar_objects_from_sinks_and_sn():
    """&STELLAR_PARAMS: sink growth spawns IMF-sampled stellar objects
    every stellar_msink_th of accreted mass; with sn_direct they
    explode immediately, injecting sn_e_ref thermal energy
    (pm/stellar_particle.f90, pm/sink_sn_feedback.f90)."""
    g = _blob_groups(lmin=4, lmax=5, d_in=100.0, p_in=1.0, tend=0.03,
                     refine_params={"err_grad_d": 0.2},
                     sink_params={"create_sinks": True, "n_sink": 10.0,
                                  "accretion_scheme": "threshold",
                                  "c_acc": 0.2},
                     stellar_params={"stellar_msink_th": 0.002,
                                     "imf_index": -2.35,
                                     "imf_low": 8.0, "imf_high": 120.0,
                                     "lt_t0": 0.01,
                                     "sn_e_ref": 0.02,
                                     "sn_direct": True})
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    assert sim.stellar is not None
    e0 = sim.totals()[4]
    sim.evolve(0.03, nstepmax=10)
    assert sim.sinks.n > 0 and sim.sinks.m.sum() > 0.02
    # sink growth crossed several 0.002 quanta -> objects spawned and
    # (sn_direct) exploded, dumping energy into the gas
    e1 = sim.totals()[4]
    assert e1 > e0 + 0.015         # at least one 0.02 injection
    # direct-explosion mode leaves no live objects behind
    assert sim.stellar.n == 0


def test_stellar_imf_and_lifetime():
    from ramses_tpu.pm.stellar import (StellarSpec, lifetime,
                                       sample_powerlaw)
    rng = np.random.default_rng(0)
    m = sample_powerlaw(rng, 8.0, 120.0, -2.35, 20000)
    assert 8.0 <= m.min() and m.max() <= 120.0
    # Salpeter: low-mass dominated
    assert np.median(m) < 20.0
    spec = StellarSpec(lt_t0=1.0, lt_m0=148.16, lt_a=0.238, lt_b=2.0)
    tl = lifetime(np.array([8.0, 40.0, 120.0]), spec)
    assert tl[0] > tl[1] > tl[2]          # massive stars die first


@pytest.mark.slow
def test_sink_cloud_accretion():
    """Cloud sampling (create_cloud_from_sink): the draw spreads over
    the cloud's cells instead of one host cell, mass+momentum stay
    conserved, and ir_cloud=1 reproduces host-cell-only accretion."""
    def run(ir_cloud):
        g = _blob_groups(lmin=4, lmax=5, d_in=100.0, p_in=1.0,
                         tend=0.02, refine_params={"err_grad_d": 0.2},
                         sink_params={"create_sinks": True,
                                      "n_sink": 10.0,
                                      "accretion_scheme": "threshold",
                                      "c_acc": 0.1,
                                      "ir_cloud": ir_cloud})
        sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
        m0 = sim.totals()[0]
        u_before = {l: np.asarray(sim.u[l]).copy() for l in sim.levels()}
        sim.evolve(0.02, nstepmax=4)
        return sim, m0, u_before

    sim4, m0, _ = run(4)
    assert sim4.sinks.n > 0 and sim4.sinks.m.sum() > 0
    # conservation with clouds on
    assert abs(sim4.totals()[0] + sim4.sinks.m.sum() - m0) < 1e-11
    sim1, m0b, _ = run(1)
    assert abs(sim1.totals()[0] + sim1.sinks.m.sum() - m0b) < 1e-11
    # the cloud spreads each sink's draw over >1 cell: one isolated
    # accretion pass from identical states must debit more cells
    from ramses_tpu.pm import amr_physics as ap

    def debited_cells(sim):
        u_pre = {l: np.asarray(sim.u[l]).copy() for l in sim.levels()}
        ap.sink_passes_amr(sim, 1e-3)
        n = 0
        for l in sim.levels():
            d = np.asarray(sim.u[l])[:, 0] - u_pre[l][:, 0]
            n += int((d < -1e-14).sum())
        return n
    if sim4.sinks.n and sim1.sinks.n:
        assert debited_cells(sim4) > debited_cells(sim1)
