"""Snapshot post-processing toolbox: the ``utils/f90`` workhorses.

The reference ships 56 standalone analysis programs (SURVEY.md §2.11);
beyond the projection tools in :mod:`ramses_tpu.utils.maps` and the
halo chain in :mod:`ramses_tpu.utils.halos`, this CLI covers the
remaining everyday set as subcommands over ``output_NNNNN``
directories:

  amr2cube   — resample leaf cells onto a uniform cube at a chosen
               level (``amr2cube.f90``)
  amr2cell   — dump the leaf-cell table as ascii
               (``amr2cell.f90``)
  part2cube  — CIC particle density cube (``part2cube.f90``)
  part2list  — ascii particle table (``part2list.f90``)
  histo      — mass-weighted 2D histogram of two cell fields, e.g.
               the rho-T phase diagram (``histo.f90``)
  amr2prof   — spherical radial profiles of cell fields about a
               centre (``amr2prof.f90``)
  part2prof  — radial profiles of particle mass/velocity
               (``part2prof.f90``)
  header     — print the snapshot header (``header.f90``)
  amr2cut    — 2D slice at a coordinate (``amr2cut.f90``)
  amr2cylprof / part2cylprof — cylindrical profiles incl. v_phi
               (``amr2cylprof.f90``, ``part2cylprof.f90``, the
               rotation curve of ``vrot.f90``)
  part2birth — star table with birth times (``part2birth.f90``,
               ``getstarlist.f90``)
  part2sfr   — star-formation history (``part2sfr.f90``)
  partcenter — shrinking-sphere particle centre (``partcenter.f90``)
  sod        — 1D axis profile for shock-tube runs (``sod.f90``)

Everything reads through :mod:`ramses_tpu.io.reader` and writes plain
ascii / .npy — small host-side numpy passes, like the originals.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

import numpy as np

from ramses_tpu.io import reader as rdr


def _cells(outdir: str):
    snap = rdr.load_snapshot(outdir)
    return snap, rdr.leaf_cells(snap)


def amr2cube(outdir: str, var: str = "density",
             lmax: Optional[int] = None) -> np.ndarray:
    """Uniform cube of ``var`` at level ``lmax``: leaves coarser than
    lmax block-fill their 2^(d·Δl) covered cells, finer ones (none, by
    leaf definition, unless lmax < levelmax) volume-average."""
    snap, cells = _cells(outdir)
    ndim = snap["info"]["ndim"]
    boxlen = snap["amr"][0].header["boxlen"]
    levels = cells["level"].astype(int)
    if lmax is None:
        lmax = int(levels.max())
    n = 1 << lmax
    dxf = boxlen / n
    acc = np.zeros((n,) * ndim)
    wacc = np.zeros((n,) * ndim)
    vals = cells[var]
    for l in np.unique(levels):
        sel = levels == l
        if not sel.any():
            continue
        pos = np.stack([cells["xyz"[d]][sel] for d in range(ndim)],
                       axis=1)
        v = vals[sel]
        if l >= lmax:
            # deposit (volume-weighted average inside the fine cell)
            idx = tuple(np.clip((pos[:, d] / dxf).astype(int), 0, n - 1)
                        for d in range(ndim))
            w = (2.0 ** (lmax - l)) ** ndim
            np.add.at(acc, idx, v * w)
            np.add.at(wacc, idx, w)
        else:
            # block-fill the 2^Δl span of each coarse leaf
            span = 1 << (lmax - l)
            i0 = np.clip(((pos - 0.5 * cells["dx"][sel][:, None])
                          / dxf).round().astype(int), 0, n - span)
            for k in range(len(v)):
                sl = tuple(slice(i0[k, d], i0[k, d] + span)
                           for d in range(ndim))
                acc[sl] += v[k]
                wacc[sl] += 1.0
    return acc / np.maximum(wacc, 1e-300)


def amr2cell(outdir: str, path: str, variables=None) -> int:
    """Leaf-cell ascii table: x y z dx level vars..."""
    snap, cells = _cells(outdir)
    ndim = snap["info"]["ndim"]
    variables = variables or snap["var_names"]
    cols = (["xyz"[d] for d in range(ndim)] + ["dx", "level"]
            + list(variables))
    data = np.stack([cells[c] for c in cols], axis=1)
    np.savetxt(path, data, header=" ".join(cols))
    return len(data)


def part2cube(outdir: str, n: int = 64) -> np.ndarray:
    """CIC particle density cube [code mass / code volume]."""
    from ramses_tpu.utils.halos import load_particles
    x, _v, m, _i, boxlen, _t = load_particles(outdir)
    ndim = x.shape[1]
    dx = boxlen / n
    s = x / dx - 0.5
    i0 = np.floor(s).astype(int)
    frac = s - i0
    cube = np.zeros((n,) * ndim)
    for corner in range(1 << ndim):
        idx = []
        w = m.copy()
        for d in range(ndim):
            b = (corner >> d) & 1
            idx.append(np.mod(i0[:, d] + b, n))
            w = w * (frac[:, d] if b else 1.0 - frac[:, d])
        np.add.at(cube, tuple(idx), w)
    return cube / dx ** ndim


def part2map(outdir: str, n: int = 256, axis: str = "z",
             family: str = "all") -> np.ndarray:
    """CIC particle surface-density map along an axis
    (``part2map.f90``): [code mass / code area].  ``family``:
    all|dm|stars selects the deposited population."""
    import ramses_tpu.io.reader as rdr
    snap = rdr.load_snapshot(outdir)
    boxlen = snap["amr"][0].header["boxlen"]
    ndim = snap["amr"][0].header["ndim"]
    dims_all = "xyz"[:ndim]
    x = np.stack([np.concatenate([pp[f"position_{d}"]
                                  for pp in snap["part"]])
                  for d in dims_all], axis=1)
    m = np.concatenate([pp["mass"] for pp in snap["part"]])
    if family != "all":
        fam = np.concatenate([pp["family"] for pp in snap["part"]])
        want = {"dm": 1, "stars": 2}[family]
        sel = fam == want
        x, m = x[sel], m[sel]
    ax = "xyz".index(axis) if ndim == 3 else 2
    dims = [d for d in range(ndim) if d != ax][:2]
    dx = boxlen / n
    s2 = x[:, dims] / dx - 0.5
    i0 = np.floor(s2).astype(int)
    frac = s2 - i0
    mp = np.zeros((n, n) if len(dims) == 2 else (n,))
    for corner in range(1 << len(dims)):
        idx = []
        w = m.copy()
        for k in range(len(dims)):
            b = (corner >> k) & 1
            idx.append(np.mod(i0[:, k] + b, n))
            w = w * (frac[:, k] if b else 1.0 - frac[:, k])
        np.add.at(mp, tuple(idx), w)
    return mp / dx ** len(dims)


def vrot(outdir: str, center, axis: str = "z",
         nbins: int = 32):
    """Particle rotation curve about an axis (``vrot.f90``):
    mass-weighted mean tangential velocity per cylindrical radius
    bin.  Returns (r_bins, v_rot)."""
    from ramses_tpu.utils.halos import load_particles
    x, v, m, _i, boxlen, _t = load_particles(outdir)
    ndim = x.shape[1]
    ax = "xyz".index(axis) if ndim == 3 else 2
    dims = [d for d in range(ndim) if d != ax][:2]
    c = np.asarray(center, dtype=np.float64)[:ndim]
    rel = x - c[None, :]
    rel -= boxlen * np.round(rel / boxlen)
    rr = np.sqrt((rel[:, dims] ** 2).sum(1))
    # tangential unit vector in the plane: (-y, x)/r
    tx, ty = -rel[:, dims[1]], rel[:, dims[0]]
    nrm = np.maximum(rr, 1e-300)
    vt = (v[:, dims[0]] * tx + v[:, dims[1]] * ty) / nrm
    edges = np.linspace(0.0, rr.max() + 1e-12, nbins + 1)
    ib = np.clip(np.searchsorted(edges, rr, side="right") - 1, 0,
                 nbins - 1)
    msum = np.bincount(ib, weights=m, minlength=nbins)
    vsum = np.bincount(ib, weights=m * vt, minlength=nbins)
    rmid = 0.5 * (edges[1:] + edges[:-1])
    return rmid, vsum / np.maximum(msum, 1e-300)


def getstarlist(outdir: str, path: str) -> int:
    """Star-particle table: id x.. v.. m birth_time metallicity
    (``getstarlist.f90``)."""
    import ramses_tpu.io.reader as rdr
    snap = rdr.load_snapshot(outdir)
    parts = {}
    for k in snap["part"][0]:
        v = [pp[k] for pp in snap["part"]]
        if isinstance(v[0], np.ndarray):
            parts[k] = np.concatenate(v)
    sel = parts["family"] == 2
    ndim = snap["amr"][0].header["ndim"]
    dims = "xyz"[:ndim]
    cols = [parts["identity"][sel]]
    cols += [parts[f"position_{d}"][sel] for d in dims]
    cols += [parts[f"velocity_{d}"][sel] for d in dims]
    cols.append(parts["mass"][sel])
    cols.append(parts.get("birth_time", np.zeros(len(parts["mass"])))[sel])
    cols.append(parts.get("metallicity",
                          np.zeros(len(parts["mass"])))[sel])
    hdr = ("id " + " ".join(dims) + " "
           + " ".join("v" + d for d in dims) + " m tp zp")
    np.savetxt(path, np.stack(cols, axis=1), header=hdr)
    return int(sel.sum())


def part2list(outdir: str, path: str) -> int:
    """Ascii particle table: id x.. v.. m."""
    from ramses_tpu.utils.halos import load_particles
    x, v, m, ids, _bl, _t = load_particles(outdir)
    data = np.concatenate([ids[:, None], x, v, m[:, None]], axis=1)
    nd = x.shape[1]
    hdr = ("id " + " ".join("xyz"[:nd]) + " "
           + " ".join("v" + c for c in "xyz"[:nd]) + " m")
    np.savetxt(path, data, header=hdr)
    return len(data)


def histo(outdir: str, var_x: str = "density", var_y: str = "pressure",
          nbins: int = 64, logx: bool = True, logy: bool = True):
    """Mass-weighted 2D histogram (the rho-T phase diagram of
    ``histo.f90``).  Returns (H, x_edges, y_edges)."""
    snap, cells = _cells(outdir)
    ndim = snap["info"]["ndim"]

    def field(name):
        if name == "temperature":              # P/rho convenience alias
            return cells["pressure"] / np.maximum(cells["density"],
                                                  1e-300)
        return cells[name]

    vx = field(var_x)
    vy = field(var_y)
    w = cells["density"] * cells["dx"] ** ndim
    fx = np.log10(np.maximum(vx, 1e-300)) if logx else vx
    fy = np.log10(np.maximum(vy, 1e-300)) if logy else vy
    H, xe, ye = np.histogram2d(fx, fy, bins=nbins, weights=w)
    return H, xe, ye


def _radial_bins(r, w, vals, nbins, rmax):
    edges = np.linspace(0.0, rmax, nbins + 1)
    which = np.clip(np.digitize(r, edges) - 1, 0, nbins - 1)
    wsum = np.bincount(which, weights=w, minlength=nbins)
    out = {}
    for name, v in vals.items():
        s = np.bincount(which, weights=w * v, minlength=nbins)
        out[name] = s / np.maximum(wsum, 1e-300)
    r_mid = 0.5 * (edges[:-1] + edges[1:])
    return r_mid, wsum, out


def amr2prof(outdir: str, center, nbins: int = 32,
             rmax: Optional[float] = None):
    """Mass-weighted spherical profiles of density/pressure/|v| about
    ``center`` (``amr2prof.f90``).  Returns (r, m_shell, profiles)."""
    snap, cells = _cells(outdir)
    ndim = snap["info"]["ndim"]
    boxlen = snap["amr"][0].header["boxlen"]
    rmax = rmax if rmax is not None else 0.5 * boxlen
    pos = np.stack([cells["xyz"[d]] for d in range(ndim)], axis=1)
    rel = pos - np.asarray(center)[:ndim]
    rel = rel - boxlen * np.round(rel / boxlen)
    r = np.sqrt((rel ** 2).sum(axis=1))
    vol = cells["dx"] ** ndim
    mass = cells["density"] * vol
    vmag = np.sqrt(sum(cells[f"velocity_{'xyz'[d]}"] ** 2
                       for d in range(ndim)))
    vals = {"density": cells["density"],
            "pressure": cells["pressure"], "v": vmag}
    return _radial_bins(r, mass, vals, nbins, rmax)


def part2prof(outdir: str, center, nbins: int = 32,
              rmax: Optional[float] = None):
    """Radial particle mass/velocity profiles (``part2prof.f90``)."""
    from ramses_tpu.utils.halos import load_particles
    x, v, m, _i, boxlen, _t = load_particles(outdir)
    nd = x.shape[1]
    rmax = rmax if rmax is not None else 0.5 * boxlen
    rel = x - np.asarray(center)[:nd]
    rel = rel - boxlen * np.round(rel / boxlen)
    r = np.sqrt((rel ** 2).sum(axis=1))
    vr = (rel * v).sum(axis=1) / np.maximum(r, 1e-300)
    return _radial_bins(r, m, {"vr": vr,
                               "v": np.sqrt((v ** 2).sum(axis=1))},
                        nbins, rmax)


def amr2cut(outdir: str, var: str = "density", axis: int = 2,
            coord: float = 0.5, lmax: Optional[int] = None) -> np.ndarray:
    """2D slice of ``var`` through ``coord`` (box units) normal to
    ``axis`` at level ``lmax`` (``amr2cut.f90``): leaves whose span
    covers the cut plane block-fill their footprint."""
    snap, cells = _cells(outdir)
    ndim = snap["info"]["ndim"]
    if ndim < 3:
        raise ValueError("amr2cut needs a 3D snapshot")
    boxlen = snap["amr"][0].header["boxlen"]
    levels = cells["level"].astype(int)
    if lmax is None:
        lmax = int(levels.max())
    n = 1 << lmax
    dxf = boxlen / n
    # half-open containment with an epsilon nudge: a cut on a cell
    # face (the default coord=0.5 always is) must pick ONE layer
    xcut = coord * boxlen * (1.0 + 1e-9) + 1e-300
    axes2d = [d for d in range(3) if d != axis]
    acc = np.zeros((n, n))
    wacc = np.zeros((n, n))
    vals = cells[var]
    pos = np.stack([cells["xyz"[d]] for d in range(3)], axis=1)
    hit = ((pos[:, axis] - 0.5 * cells["dx"] <= xcut)
           & (xcut < pos[:, axis] + 0.5 * cells["dx"]))
    for l in np.unique(levels[hit]):
        sel = hit & (levels == l)
        v = vals[sel]
        p2 = pos[sel][:, axes2d]
        if l >= lmax:
            # in-plane area weight: pixels mixing two fine levels
            # average by covered area (cf. amr2cube's volume weight)
            w = (2.0 ** (lmax - l)) ** 2
            idx = tuple(np.clip((p2[:, k] / dxf).astype(int), 0, n - 1)
                        for k in range(2))
            np.add.at(acc, idx, v * w)
            np.add.at(wacc, idx, w)
        else:
            span = 1 << (lmax - l)
            i0 = np.clip(((p2 - 0.5 * cells["dx"][sel][:, None])
                          / dxf).round().astype(int), 0, n - span)
            for k in range(len(v)):
                sl = (slice(i0[k, 0], i0[k, 0] + span),
                      slice(i0[k, 1], i0[k, 1] + span))
                acc[sl] += v[k]
                wacc[sl] += 1.0
    return acc / np.maximum(wacc, 1e-300)


def _cyl_coords(rel, axis: int):
    """(R, z, perp axes) cylindrical decomposition about ``axis``.
    2D snapshots: only the out-of-plane axis (axis >= ndim, i.e.
    ``--dir z``) is a valid rotation axis; z = 0 there."""
    nd = rel.shape[1]
    perp = [d for d in range(nd) if d != axis][:2]
    if len(perp) < 2:
        raise ValueError(
            f"rotation axis {axis} leaves {len(perp)} in-plane axes in "
            f"a {nd}D snapshot; a cylindrical profile needs 2 "
            "(2D runs: use the out-of-plane --dir z)")
    R = np.sqrt(sum(rel[:, d] ** 2 for d in perp))
    z = rel[:, axis] if axis < nd else np.zeros(len(rel))
    return R, z, perp


def _vphi(rel, vel, perp, R):
    """Tangential velocity (r x v)_axis / R on the ``perp`` plane."""
    return ((rel[:, perp[0]] * vel[:, perp[1]]
             - rel[:, perp[1]] * vel[:, perp[0]])
            / np.maximum(R, 1e-300))


def amr2cylprof(outdir: str, center, axis: int = 2, nbins: int = 32,
                rmax: Optional[float] = None,
                zmax: Optional[float] = None):
    """Cylindrical gas profiles about ``center`` (``amr2cylprof.f90``):
    mass-weighted density/pressure/v_phi vs cylindrical radius inside
    |z| < zmax.  Returns (R, m_ring, profiles)."""
    snap, cells = _cells(outdir)
    ndim = snap["info"]["ndim"]
    boxlen = snap["amr"][0].header["boxlen"]
    rmax = rmax if rmax is not None else 0.5 * boxlen
    zmax = zmax if zmax is not None else 0.5 * boxlen
    pos = np.stack([cells["xyz"[d]] for d in range(ndim)], axis=1)
    rel = pos - np.asarray(center)[:ndim]
    rel = rel - boxlen * np.round(rel / boxlen)
    R, z, perp = _cyl_coords(rel, axis)
    sel = np.abs(z) < zmax if ndim == 3 else np.ones(len(R), bool)
    vel = np.stack([cells[f"velocity_{'xyz'[d]}"] for d in range(ndim)],
                   axis=1)
    vphi = _vphi(rel, vel, perp, R)
    mass = cells["density"] * cells["dx"] ** ndim
    vals = {"density": cells["density"],
            "pressure": cells["pressure"], "vphi": vphi}
    return _radial_bins(R[sel], mass[sel],
                        {k: v[sel] for k, v in vals.items()},
                        nbins, rmax)


def part2cylprof(outdir: str, center, axis: int = 2, nbins: int = 32,
                 rmax: Optional[float] = None):
    """Cylindrical particle profiles: surface density + rotation curve
    (``part2cylprof.f90``/``vrot.f90``)."""
    from ramses_tpu.utils.halos import load_particles
    x, v, m, _i, boxlen, _t = load_particles(outdir)
    nd = x.shape[1]
    rmax = rmax if rmax is not None else 0.5 * boxlen
    rel = x - np.asarray(center)[:nd]
    rel = rel - boxlen * np.round(rel / boxlen)
    R, _z, perp = _cyl_coords(rel, axis)
    vphi = _vphi(rel, v, perp, R)
    return _radial_bins(R, m, {"vphi": vphi,
                               "v": np.sqrt((v ** 2).sum(axis=1))},
                        nbins, rmax)


def part2birth(outdir: str, path: str) -> int:
    """Star-particle table with birth times/metallicities
    (``part2birth.f90`` / ``getstarlist.f90``)."""
    snap = rdr.load_snapshot(outdir)
    if "part" not in snap:
        raise ValueError(f"{outdir}: no particle files")
    parts = {}
    first = snap["part"][0]
    for k, v in first.items():
        if isinstance(v, np.ndarray):
            parts[k] = np.concatenate([p[k] for p in snap["part"]])
    from ramses_tpu.pm.particles import FAM_STAR
    fam = parts.get("family")
    if fam is not None:
        star = fam == FAM_STAR
    elif "birth_time" in parts:
        # older outputs without family codes: stars are the particles
        # with a birth record (part2birth.f90's tp /= 0 test)
        star = parts["birth_time"] != 0.0
    else:
        star = np.ones(len(parts["mass"]), bool)
    nd = snap["info"]["ndim"]
    cols = [parts["identity"][star]]
    hdr = ["id"]
    for d in range(nd):
        cols.append(parts[f"position_{'xyz'[d]}"][star])
        hdr.append("xyz"[d])
    cols.append(parts["mass"][star])
    hdr.append("m")
    for k, name in (("birth_time", "t_birth"), ("metallicity", "Z")):
        if k in parts:
            cols.append(parts[k][star])
            hdr.append(name)
    np.savetxt(path, np.stack(cols, axis=1), header=" ".join(hdr))
    return int(star.sum())


def part2sfr(outdir: str, nbins: int = 32):
    """Star-formation history: SFR per birth-time bin [code mass /
    code time] (``part2sfr.f90``).  Returns (t_mid, sfr)."""
    snap = rdr.load_snapshot(outdir)
    if "part" not in snap:
        raise ValueError(f"{outdir}: no particle files")
    tp, m, fam = [], [], []
    for p in snap["part"]:
        if "birth_time" not in p:
            continue
        tp.append(p["birth_time"])
        m.append(p["mass"])
        fam.append(p.get("family", np.full(len(p["mass"]), 2)))
    if not tp:
        raise ValueError(f"{outdir}: no star birth records")
    from ramses_tpu.pm.particles import FAM_STAR
    tp = np.concatenate(tp)
    m = np.concatenate(m)
    star = (np.concatenate(fam) == FAM_STAR) & (tp > 0)
    if not star.any():
        raise ValueError(f"{outdir}: no star birth records")
    edges = np.linspace(0.0, max(float(tp[star].max()), 1e-300),
                        nbins + 1)
    msum, _ = np.histogram(tp[star], bins=edges, weights=m[star])
    dt = np.diff(edges)
    return 0.5 * (edges[:-1] + edges[1:]), msum / np.maximum(dt, 1e-300)


def partcenter(outdir: str, niter: int = 16) -> np.ndarray:
    """Shrinking-sphere centre of the particle distribution
    (``partcenter.f90``)."""
    from ramses_tpu.utils.halos import load_particles
    x, _v, m, _i, boxlen, _t = load_particles(outdir)
    nd = x.shape[1]
    c = (x * m[:, None]).sum(0) / m.sum()
    r = 0.5 * boxlen
    for _ in range(niter):
        rel = x - c
        rel = rel - boxlen * np.round(rel / boxlen)
        sel = (rel ** 2).sum(1) < r * r
        if sel.sum() < 8:
            break
        c = c + (rel[sel] * m[sel, None]).sum(0) / m[sel].sum()
        c = np.mod(c, boxlen)
        r *= 0.75
    return c


def sod(outdir: str, axis: int = 0):
    """1D profile along ``axis`` through the box centre — the
    shock-tube comparison columns (``sod.f90``).  Returns
    (x, rho, v_axis, P)."""
    snap, cells = _cells(outdir)
    ndim = snap["info"]["ndim"]
    if axis >= ndim:
        raise ValueError(f"sod axis {axis} >= snapshot ndim {ndim}")
    boxlen = snap["amr"][0].header["boxlen"]
    pos = np.stack([cells["xyz"[d]] for d in range(ndim)], axis=1)
    sel = np.ones(len(pos), bool)
    # half-open cell containment: the mid-plane often lies exactly on
    # a cell face, which must pick ONE neighbour, not both
    xs = 0.5 * boxlen * (1.0 + 1e-9)
    for d in range(ndim):
        if d != axis:
            sel &= ((pos[:, d] - 0.5 * cells["dx"] <= xs)
                    & (xs < pos[:, d] + 0.5 * cells["dx"]))
    order = np.argsort(pos[sel, axis])
    x = pos[sel, axis][order]
    return (x, cells["density"][sel][order],
            cells[f"velocity_{'xyz'[axis]}"][sel][order],
            cells["pressure"][sel][order])


def header(outdir: str) -> dict:
    """Snapshot header summary (``header.f90``)."""
    snap = rdr.load_snapshot(outdir)
    h = snap["amr"][0].header
    info = snap["info"]
    out = dict(ndim=h["ndim"], nlevelmax=h["nlevelmax"],
               boxlen=h["boxlen"], t=h["t"], aexp=h.get("aexp", 1.0),
               nstep=h["nstep"], ncpu=len(snap["amr"]),
               vars=snap["var_names"])
    if "part" in snap:
        out["npart"] = sum(len(p["mass"]) for p in snap["part"])
    out.update({k: info[k] for k in ("unit_l", "unit_d", "unit_t")
                if k in info})
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ramses_tpu.utils.post")
    sub = ap.add_subparsers(dest="tool", required=True)

    p = sub.add_parser("amr2cube")
    p.add_argument("outdir")
    p.add_argument("npyfile")
    p.add_argument("--var", default="density")
    p.add_argument("--lmax", type=int, default=None)

    p = sub.add_parser("amr2cell")
    p.add_argument("outdir")
    p.add_argument("txtfile")

    p = sub.add_parser("part2cube")
    p.add_argument("outdir")
    p.add_argument("npyfile")
    p.add_argument("--n", type=int, default=64)

    p = sub.add_parser("part2list")
    p.add_argument("outdir")
    p.add_argument("txtfile")

    p = sub.add_parser("histo")
    p.add_argument("outdir")
    p.add_argument("npyfile")
    p.add_argument("--x", default="density")
    p.add_argument("--y", default="temperature")
    p.add_argument("--nbins", type=int, default=64)

    p = sub.add_parser("amr2prof")
    p.add_argument("outdir")
    p.add_argument("txtfile")
    p.add_argument("--center", type=float, nargs="+",
                   default=[0.5, 0.5, 0.5])
    p.add_argument("--nbins", type=int, default=32)

    p = sub.add_parser("part2prof")
    p.add_argument("outdir")
    p.add_argument("txtfile")
    p.add_argument("--center", type=float, nargs="+",
                   default=[0.5, 0.5, 0.5])
    p.add_argument("--nbins", type=int, default=32)

    p = sub.add_parser("header")
    p.add_argument("outdir")

    p = sub.add_parser("amr2cut")
    p.add_argument("outdir")
    p.add_argument("npyfile")
    p.add_argument("--var", default="density")
    p.add_argument("--dir", default="z", choices=["x", "y", "z"])
    p.add_argument("--coord", type=float, default=0.5)
    p.add_argument("--lmax", type=int, default=None)

    for name in ("amr2cylprof", "part2cylprof"):
        p = sub.add_parser(name)
        p.add_argument("outdir")
        p.add_argument("txtfile")
        p.add_argument("--center", type=float, nargs="+",
                       default=[0.5, 0.5, 0.5])
        p.add_argument("--dir", default="z", choices=["x", "y", "z"])
        p.add_argument("--nbins", type=int, default=32)

    p = sub.add_parser("part2birth")
    p.add_argument("outdir")
    p.add_argument("txtfile")

    p = sub.add_parser("part2sfr")
    p.add_argument("outdir")
    p.add_argument("txtfile")
    p.add_argument("--nbins", type=int, default=32)

    p = sub.add_parser("partcenter")
    p.add_argument("outdir")

    p = sub.add_parser("sod")
    p.add_argument("outdir")
    p.add_argument("txtfile")
    p.add_argument("--dir", default="x", choices=["x", "y", "z"])

    p = sub.add_parser("part2map")
    p.add_argument("outdir")
    p.add_argument("npyfile")
    p.add_argument("--n", type=int, default=256)
    p.add_argument("--dir", default="z", choices=["x", "y", "z"])
    p.add_argument("--family", default="all",
                   choices=["all", "dm", "stars"])

    p = sub.add_parser("vrot")
    p.add_argument("outdir")
    p.add_argument("txtfile")
    p.add_argument("--center", type=float, nargs="+",
                   default=[0.5, 0.5, 0.5])
    p.add_argument("--dir", default="z", choices=["x", "y", "z"])
    p.add_argument("--nbins", type=int, default=32)

    p = sub.add_parser("getstarlist")
    p.add_argument("outdir")
    p.add_argument("txtfile")

    args = ap.parse_args(argv)
    if args.tool == "amr2cube":
        cube = amr2cube(args.outdir, var=args.var, lmax=args.lmax)
        np.save(args.npyfile, cube)
        print(f"amr2cube: {cube.shape} -> {args.npyfile} "
              f"(min {cube.min():.4e} max {cube.max():.4e})")
    elif args.tool == "amr2cell":
        n = amr2cell(args.outdir, args.txtfile)
        print(f"amr2cell: {n} leaves -> {args.txtfile}")
    elif args.tool == "part2cube":
        cube = part2cube(args.outdir, n=args.n)
        np.save(args.npyfile, cube)
        print(f"part2cube: {cube.shape} -> {args.npyfile}")
    elif args.tool == "part2list":
        n = part2list(args.outdir, args.txtfile)
        print(f"part2list: {n} particles -> {args.txtfile}")
    elif args.tool == "part2map":
        mp = part2map(args.outdir, n=args.n, axis=args.dir,
                      family=args.family)
        np.save(args.npyfile, mp)
        print(f"part2map: {mp.shape} {args.family} -> {args.npyfile}")
    elif args.tool == "vrot":
        r, vr = vrot(args.outdir, args.center, axis=args.dir,
                     nbins=args.nbins)
        np.savetxt(args.txtfile, np.stack([r, vr], axis=1),
                   header="r v_rot")
        print(f"vrot: {args.nbins} bins -> {args.txtfile}")
    elif args.tool == "getstarlist":
        n = getstarlist(args.outdir, args.txtfile)
        print(f"getstarlist: {n} stars -> {args.txtfile}")
    elif args.tool == "histo":
        H, xe, ye = histo(args.outdir, var_x=args.x, var_y=args.y,
                          nbins=args.nbins)
        np.savez(args.npyfile, H=H, x_edges=xe, y_edges=ye)
        print(f"histo: {H.shape} {args.x}-{args.y} -> {args.npyfile}")
    elif args.tool == "amr2prof":
        r, msh, prof = amr2prof(args.outdir, args.center,
                                nbins=args.nbins)
        cols = [r, msh] + [prof[k] for k in sorted(prof)]
        np.savetxt(args.txtfile, np.stack(cols, axis=1),
                   header="r m_shell " + " ".join(sorted(prof)))
        print(f"amr2prof: {args.nbins} bins -> {args.txtfile}")
    elif args.tool == "part2prof":
        r, msh, prof = part2prof(args.outdir, args.center,
                                 nbins=args.nbins)
        cols = [r, msh] + [prof[k] for k in sorted(prof)]
        np.savetxt(args.txtfile, np.stack(cols, axis=1),
                   header="r m_shell " + " ".join(sorted(prof)))
        print(f"part2prof: {args.nbins} bins -> {args.txtfile}")
    elif args.tool == "header":
        for k, v in header(args.outdir).items():
            print(f"{k:12s} {v}")
    elif args.tool == "amr2cut":
        m = amr2cut(args.outdir, var=args.var,
                    axis="xyz".index(args.dir), coord=args.coord,
                    lmax=args.lmax)
        np.save(args.npyfile, m)
        print(f"amr2cut: {m.shape} slice -> {args.npyfile} "
              f"(min {m.min():.4e} max {m.max():.4e})")
    elif args.tool in ("amr2cylprof", "part2cylprof"):
        fn = amr2cylprof if args.tool == "amr2cylprof" else part2cylprof
        r, msh, prof = fn(args.outdir, args.center,
                          axis="xyz".index(args.dir), nbins=args.nbins)
        cols = [r, msh] + [prof[k] for k in sorted(prof)]
        np.savetxt(args.txtfile, np.stack(cols, axis=1),
                   header="R m_ring " + " ".join(sorted(prof)))
        print(f"{args.tool}: {args.nbins} bins -> {args.txtfile}")
    elif args.tool == "part2birth":
        n = part2birth(args.outdir, args.txtfile)
        print(f"part2birth: {n} stars -> {args.txtfile}")
    elif args.tool == "part2sfr":
        t, sfr = part2sfr(args.outdir, nbins=args.nbins)
        np.savetxt(args.txtfile, np.stack([t, sfr], axis=1),
                   header="t sfr")
        print(f"part2sfr: {args.nbins} bins -> {args.txtfile}")
    elif args.tool == "partcenter":
        c = partcenter(args.outdir)
        print(" ".join(f"{v:.8f}" for v in c))
    elif args.tool == "sod":
        x, rho, v, press = sod(args.outdir, axis="xyz".index(args.dir))
        np.savetxt(args.txtfile, np.stack([x, rho, v, press], axis=1),
                   header="x rho v P")
        print(f"sod: {len(x)} cells -> {args.txtfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
