"""Ideal MHD with constrained transport (SURVEY.md §2.3).

TPU-native re-design of the reference ``mhd/`` solver: cell-centered
conservative state plus staggered face-centered B, whole-grid fused
kernels, Gardiner-Stone arithmetic EMF averaging for the corner problem,
HLLD/HLL/LLF interface solvers.
"""
