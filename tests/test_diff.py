"""Differentiable solver subsystem (``ramses_tpu/diff``).

Pins the subsystem's three contracts:

  * gradient-safe kernels — finite-difference-vs-AD gradchecks over the
    hot hydro path (every Riemann solver, every slope limiter, the
    barotropic EOS forms, the Courant reduction), including the
    degenerate identical-state interfaces where the raw double-where
    hazard used to NaN-poison reverse-mode cotangents;
  * checkpointed adjoint rollouts — the forward pass of the
    remat-windowed scan is BITWISE identical to the undifferentiated
    hydro driver (the MHD CT chain matches to <=2 ulp; XLA fuses it
    differently under remat), and the end-to-end Sedov loss gradient
    matches central differences at rtol 1e-3 in f64;
  * the calibration service — loss descends, optimizer-state
    checkpoints resume mid-run bit-reproducibly, diverged members
    quarantine, ``calibrate``-kind jobs thread through the queue, and
    the undifferentiated drivers never import the diff package
    (zero-overhead pin).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax

from ramses_tpu.config import params_from_dict
from ramses_tpu.hydro import eos, muscl, riemann
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.hydro.timestep import compute_dt

pytestmark = pytest.mark.smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------
# FD-vs-AD gradcheck helpers
# ---------------------------------------------------------------------
def _fd_grad(f, x, eps=1e-6):
    """Dense central-difference gradient of scalar ``f`` at ``x``."""
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    for _ in it:
        i = it.multi_index
        xp = x.copy()
        xp[i] += eps
        xm = x.copy()
        xm[i] -= eps
        g[i] = (float(f(jnp.asarray(xp)))
                - float(f(jnp.asarray(xm)))) / (2 * eps)
    return g


def _gradcheck(f, x, rtol=1e-3):
    ad = np.asarray(jax.grad(f)(jnp.asarray(np.asarray(x, np.float64))))
    assert np.all(np.isfinite(ad)), "non-finite AD gradient"
    fd = _fd_grad(f, x)
    denom = np.maximum(np.abs(fd), 1e-8 * np.max(np.abs(fd)) + 1e-12)
    rel = np.max(np.abs(ad - fd) / denom)
    assert rel < rtol, f"max rel FD/AD mismatch {rel:.3e}"


# ---------------------------------------------------------------------
# per-kernel gradchecks (the double-where fixes)
# ---------------------------------------------------------------------
@pytest.mark.parametrize("solver",
                         ["llf", "hll", "hllc", "exact", "acoustic"])
def test_riemann_gradcheck(solver):
    """FD-vs-AD through every interface solver, including degenerate
    identical-state interfaces (zero-strength waves — the lanes whose
    raw sqrt/pow/div derivatives used to be NaN)."""
    rng = np.random.default_rng(0)
    # FD cost is O(N * nvar) full solves; the iterative exact solver is
    # ~10x the closed-form ones per solve, so it gets a smaller batch
    # (still covering both degenerate and generic interfaces)
    N = 12 if solver == "exact" else 32
    cfg = HydroStatic(ndim=2, riemann=solver)
    ql = np.stack([1.0 + 0.3 * rng.random(N),
                   0.2 * rng.standard_normal(N),
                   1.0 + 0.3 * rng.random(N),
                   0.1 * rng.standard_normal(N)])
    qr = ql + 0.1 * rng.standard_normal(ql.shape)
    qr[:, :8] = ql[:, :8]          # identical states -> degenerate waves
    w = rng.standard_normal((cfg.nvar + 1, N))

    def f(x):
        return jnp.sum(w * riemann.solve(ql + 0.5 * x, jnp.asarray(qr),
                                         cfg))

    _gradcheck(f, np.zeros(ql.shape) + 0.01)


@pytest.mark.parametrize("st", [1, 2, 3, 7, 8])
def test_uslope_gradcheck(st):
    """Every slope limiter (slope_type), including the van Leer form
    (st=7) whose harmonic-mean denominator vanishes at extrema."""
    rng = np.random.default_rng(st)
    cfg = HydroStatic(ndim=2, slope_type=st)
    q = 1.0 + 0.1 * rng.standard_normal((cfg.nvar, 8, 8))
    w = rng.standard_normal((cfg.ndim, cfg.nvar, 8, 8))

    def f(x):
        return jnp.sum(w * muscl.uslope(x, cfg))

    _gradcheck(f, q)


@pytest.mark.parametrize("form", ["isothermal", "polytrope",
                                  "double_polytrope", "custom"])
def test_eos_gradcheck(form):
    """Barotropic EOS forms — the 'custom' branch evaluates a fractional
    power at x < 1 only through the guarded input."""
    rng = np.random.default_rng(3)
    nH = np.concatenate([0.3 + 0.4 * rng.random(8),
                         1.0 + 2.0 * rng.random(8)])
    w = rng.standard_normal(16)

    def f(x):
        return jnp.sum(w * eos.barotropic_eos_temperature(
            x, form, 10.0, 1.0, 0.7))

    _gradcheck(f, nH)


def test_compute_dt_gradcheck():
    """The Courant reduction (min over cells) is differentiable — its
    subgradient picks the argmin cell and FD agrees away from ties."""
    rng = np.random.default_rng(7)
    cfg = HydroStatic(ndim=2)
    u = np.stack([1.0 + 0.2 * rng.random((8, 8)),
                  0.1 * rng.standard_normal((8, 8)),
                  0.1 * rng.standard_normal((8, 8)),
                  2.0 + 0.5 * rng.random((8, 8))])

    def f(x):
        return compute_dt(x, None, 0.1, cfg)

    _gradcheck(f, u)


# ---------------------------------------------------------------------
# rollout: bitwise forward pin + e2e loss gradcheck
# ---------------------------------------------------------------------
def _sedov_params(niter=10, nmember=2, nsteps=5, nml_extra=None):
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "point"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "length_x": [10.0, 1.0], "length_y": [10.0, 1.0],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.0],
                        "p_region": [1e-5, 0.1]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.8,
                         "riemann": "llf"},
        "output_params": {"noutput": 1, "tout": [0.02]},
        "calibration_params": {"fit_gamma": True, "nsteps": nsteps,
                               "niter": niter, "lr": 0.02,
                               "nmember": nmember,
                               "guess_spread": 0.06},
    }
    if nml_extra:
        for grp, kv in nml_extra.items():
            groups.setdefault(grp, {}).update(kv)
    return params_from_dict(groups, ndim=2)


def _sedov_problem():
    from ramses_tpu.diff.calibrate import build_problem
    return build_problem(_sedov_params(), jnp.float64)


def test_forward_bitwise_pin():
    """checkpointed_run_steps == run_steps BITWISE (u, t, ndone), for
    the default sqrt window and a non-divisible inner length (padding
    iterations masked)."""
    from ramses_tpu.diff.rollout import checkpointed_run_steps
    from ramses_tpu.grid.uniform import run_steps

    grid, u0, tend = _sedov_problem()
    t0 = jnp.zeros((), u0.dtype)
    tendj = jnp.asarray(tend, u0.dtype)
    u_ref, t_ref, n_ref = run_steps(grid, u0, t0, tendj, 7)
    for inner in (None, 3):
        u_c, t_c, n_c = checkpointed_run_steps(grid, u0, t0, tendj, 7,
                                               inner=inner)
        assert np.array_equal(np.asarray(u_ref), np.asarray(u_c)), inner
        assert float(t_ref) == float(t_c)
        assert int(n_ref) == int(n_c)


def test_mhd_forward_pin():
    """rollout_mhd matches mhd.uniform.run_steps to <=2 ulp on an
    Orszag-Tang vortex (t and ndone exactly).

    Unlike the hydro chain, the MHD CT chain is NOT bitwise under the
    nested remat scan — XLA fuses the step body slightly differently
    and the states drift by one rounding ulp, independent of the inner
    window size (measured identical at inner=1..nsteps).  Pin that
    bound so a real formulation change (which would move results by
    orders of magnitude more) still trips."""
    from ramses_tpu.diff.rollout import rollout_mhd
    from ramses_tpu.mhd import core as mcore
    from ramses_tpu.mhd import uniform as mu

    n = 16
    cfg = mcore.MhdStatic(ndim=2, riemann="hlld")
    dx = 1.0 / n
    x = (np.arange(n) + 0.5) * dx
    X, Y = np.meshgrid(x, x, indexing="ij")
    rho = cfg.gamma ** 2 / (4 * np.pi) * np.ones((n, n))
    p = cfg.gamma / (4 * np.pi) * np.ones((n, n))
    vx, vy = -np.sin(2 * np.pi * Y), np.sin(2 * np.pi * X)
    B0 = 1 / np.sqrt(4 * np.pi)
    bf = np.zeros((3, n, n))
    bf[0] = -B0 * np.sin(2 * np.pi * Y)
    bf[1] = B0 * np.sin(4 * np.pi * X)
    bcx = 0.5 * (bf[0] + np.roll(bf[0], -1, 0))
    bcy = 0.5 * (bf[1] + np.roll(bf[1], -1, 1))
    e = (p / (cfg.gamma - 1) + 0.5 * rho * (vx ** 2 + vy ** 2)
         + 0.5 * (bcx ** 2 + bcy ** 2))
    u = np.zeros((8, n, n))
    u[0], u[1], u[2], u[4], u[5], u[6] = (rho, rho * vx, rho * vy, e,
                                          bcx, bcy)
    grid = mu.MhdGrid(cfg=cfg, shape=(n, n), dx=dx,
                      bc_kinds=((0, 0), (0, 0)))
    uj, bfj = jnp.asarray(u), jnp.asarray(bf)
    t0 = jnp.zeros(())
    tend = jnp.asarray(1e9)
    ref = mu.run_steps(grid, uj, bfj, t0, tend, 6)
    got = rollout_mhd(grid, uj, bfj, t0, tend, 6, inner=2)
    ulp = 2 * np.finfo(np.float64).eps
    for a, b in zip(ref[:2], got[:2]):
        a, b = np.asarray(a), np.asarray(b)
        assert np.max(np.abs(a - b)) <= ulp * max(1.0, np.max(np.abs(a)))
    assert np.array_equal(np.asarray(ref[2]), np.asarray(got[2]))  # t
    assert int(ref[3]) == int(got[3]) == 6                      # ndone


def test_e2e_sedov_loss_gradcheck():
    """End-to-end: d(loss)/d(gamma, ic_scale) through a 4-step Sedov
    rollout matches central differences at rtol 1e-3 (f64)."""
    from ramses_tpu.diff.rollout import rollout_loss
    from ramses_tpu.grid.uniform import run_steps

    grid, u0, tend = _sedov_problem()
    t0 = jnp.zeros((), u0.dtype)
    tendj = jnp.asarray(tend, u0.dtype)
    target, _, _ = run_steps(grid, u0, t0, tendj, 4)

    def loss(x):
        theta = {"gamma": x[0], "ic_scale": x[1]}
        return rollout_loss(theta, u0, target, grid, t0, tendj, 4,
                            inner=2)

    x0 = np.array([1.45, 1.05])
    assert float(loss(jnp.asarray(x0))) > 0.0
    _gradcheck(loss, x0, rtol=1e-3)


def test_no_diff_import_in_forward_drivers():
    """Zero-overhead pin: importing every undifferentiated driver layer
    must not pull in ramses_tpu.diff (the adjoint machinery is pay-for-
    use only)."""
    code = (
        "import sys\n"
        "import ramses_tpu.driver\n"
        "import ramses_tpu.grid.uniform\n"
        "import ramses_tpu.mhd.uniform\n"
        "import ramses_tpu.mhd.driver\n"
        "import ramses_tpu.ensemble.batch\n"
        "import ramses_tpu.ensemble.service\n"
        "import ramses_tpu.__main__\n"
        "bad = sorted(m for m in sys.modules"
        " if m.startswith('ramses_tpu.diff'))\n"
        "assert not bad, f'forward drivers imported {bad}'\n"
        "print('clean')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         env=env, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stderr
    assert "clean" in out.stdout


# ---------------------------------------------------------------------
# queue: calibrate-kind jobs
# ---------------------------------------------------------------------
def test_queue_job_kind(tmp_path):
    """The job record's explicit ``kind`` field: defaulted, validated,
    legacy-tolerant, and carried through the failure log."""
    from ramses_tpu.ensemble import queue as jq

    qdir = str(tmp_path / "q")
    jq.submit(qdir, "&RUN_PARAMS\n/\n")
    cal_id = jq.submit(qdir, "&RUN_PARAMS\n/\n", kind="calibrate")
    with pytest.raises(ValueError, match="unknown job kind"):
        jq.submit(qdir, "&RUN_PARAMS\n/\n", kind="optimize")

    j1 = jq.claim(qdir)
    assert jq.job_kind(j1.record) == "run"
    j2 = jq.claim(qdir)
    assert j2.id == cal_id and jq.job_kind(j2.record) == "calibrate"
    # records written before the field existed default to "run"
    assert jq.job_kind({"id": "old"}) == "run"
    # the failure log classifies each attempt by kind
    jq.requeue(j2, error="boom")
    j3 = jq.claim(qdir)
    assert j3.id == cal_id
    assert j3.record["failure_log"][-1]["kind"] == "calibrate"


# ---------------------------------------------------------------------
# calibration service: descent, checkpoint resume, quarantine
# ---------------------------------------------------------------------
@pytest.mark.slow
def test_calibration_descends_and_resumes(tmp_path):
    """A short calibration drops the loss, checkpoints optimizer state
    as manifest-valid output_NNNNN dirs, and a killed run resumed from
    the surviving mid-run checkpoint reproduces the full run's final
    parameters bit-for-bit.

    Slow tier for wall-clock only (three full compile+descend legs);
    the kill/resume path also runs end-to-end in CI's
    calibration-smoke job with a real injected SIGTERM."""
    import shutil

    from ramses_tpu.diff.calibrate import run_calibration_job

    params = _sedov_params(niter=10, nmember=2, nsteps=5)
    params.calibration.checkpoint_every = 5
    params.output.telemetry = str(tmp_path / "tel.jsonl")
    base = str(tmp_path / "cal")
    res = run_calibration_job(params, base_dir=base, log=None)
    assert res["iterations"] == 10 and res["start_iter"] == 0
    assert res["loss_final"] < res["loss_first"]
    assert res["quarantined"] == 0
    assert os.path.isdir(os.path.join(base, "output_00005"))
    assert os.path.isdir(os.path.join(base, "output_00010"))
    # telemetry carries the loss curve + step time per iteration
    import json
    events = [json.loads(l) for l in open(params.output.telemetry)]
    iters = [e for e in events if e.get("kind") == "calibrate_iter"]
    assert len(iters) == 10
    assert all("loss_min" in e and "grad_norm_max" in e
               and "step_time_s" in e for e in iters)
    assert any(e.get("kind") == "calibrate_done" for e in events)

    # kill-at-iteration-5 equivalent: only the mid-run checkpoint
    # survives; auto_resume must restart there and land on the same
    # final parameters (same compiled update sequence)
    shutil.rmtree(os.path.join(base, "output_00010"))
    params2 = _sedov_params(niter=10, nmember=2, nsteps=5)
    params2.calibration.checkpoint_every = 5
    params2.output.telemetry = str(tmp_path / "tel2.jsonl")
    params2.run.auto_resume = True
    res2 = run_calibration_job(params2, base_dir=base, log=None)
    assert res2["resumed_from"] == 5 and res2["start_iter"] == 5
    assert np.allclose(res2["gamma"], res["gamma"], rtol=0, atol=0)

    # a changed problem spec must NOT silently continue: fresh start
    params3 = _sedov_params(niter=12, nmember=2, nsteps=5)
    params3.output.telemetry = str(tmp_path / "tel3.jsonl")
    params3.run.auto_resume = True
    res3 = run_calibration_job(params3, base_dir=base, log=None)
    assert res3["resumed_from"] is None and res3["start_iter"] == 0


@pytest.mark.slow
def test_calibration_quarantines_diverged_member(tmp_path):
    """A member whose loss exceeds diverge_loss is quarantined: its
    parameters freeze, the rest of the batch keeps optimizing.

    Slow tier for wall-clock only (the B=3 vmapped update compile
    dominates) — the single-core tier-1 budget."""
    from ramses_tpu.diff.calibrate import run_calibration_job

    params = _sedov_params(niter=3, nmember=3, nsteps=4)
    # absurd threshold below the initial loss -> everyone whose loss
    # is visible on iteration 0 quarantines except none are below it;
    # use a mid-range value so only the worst guesses trip
    params.calibration.diverge_loss = 1e-30
    params.output.telemetry = str(tmp_path / "tel.jsonl")
    res = run_calibration_job(params, base_dir=str(tmp_path / "cal"),
                              log=None)
    assert res["quarantined"] == 3 and res["active"] == 0
    import json
    events = [json.loads(l) for l in open(params.output.telemetry)]
    q = [e for e in events if e.get("kind") == "quarantine"]
    assert len(q) == 3
    assert all(e["reason"] == "diverged" for e in q)


@pytest.mark.slow
def test_calibration_recovers_gamma(tmp_path):
    """Convergence: 40 Adam iterations on a 3-member batch recover the
    true EOS gamma to within 2% from a 6% off-truth spread."""
    from ramses_tpu.diff.calibrate import run_calibration_job

    params = _sedov_params(niter=40, nmember=3, nsteps=6)
    params.output.telemetry = str(tmp_path / "tel.jsonl")
    res = run_calibration_job(params, base_dir=str(tmp_path / "cal"),
                              log=None)
    truth = res["gamma_truth"]
    assert res["loss_final"] < 0.1 * res["loss_first"]
    assert abs(res["gamma_best"] - truth) / truth < 0.02
