"""ramses_tpu — a TPU-native astrophysics AMR framework.

A ground-up JAX/XLA re-design of the capabilities of RAMSES
(Fortran 90 + MPI reference surveyed in SURVEY.md): compressible
(magneto-)hydrodynamics on adaptively refined meshes, self-gravity,
particle-mesh N-body, radiative transfer, and the surrounding runtime
(config, checkpointing, observability).

Architecture (see README.md):
  * host: octree topology, refinement decisions, I/O, orchestration
  * device: dense per-level batch kernels under ``jax.jit`` — Godunov
    sweeps, multigrid relaxation, CIC deposition — sharded over a
    ``jax.sharding.Mesh`` with ring halo exchange through the
    backend-dispatched engine (``parallel/dma_halo.py``): Pallas
    async remote-copy DMA with comm/compute overlap on TPU,
    ``lax.ppermute`` elsewhere (``&AMR_PARAMS halo_backend``).
"""

__version__ = "0.1.0"

from ramses_tpu.config import Params, load_params  # noqa: F401
from ramses_tpu.platform import enable_compile_cache as _ecc

_ecc()
del _ecc
