#!/usr/bin/env python
"""Offline checkpoint scrubber: walk a run directory, verify every
checkpoint — snapshot ``output_NNNNN`` and elastic pario
``pario_NNNNN`` alike — against its manifests with FULL SHA-256
hashing, and for pario format 2 cross-check each shard's payload
against the row/oct/particle counts its manifest claims.

Per-checkpoint verdicts print to stdout; a machine-readable summary
lands as JSON (``VALIDATE_JSON`` env or ``--json``, default
``VALIDATE_CKPT.json`` — the ``tools/profile_amr.py`` convention);
exit status is nonzero when any torn checkpoint was found, so a CI leg
or cron scrub can gate on it.

Usage:  python tools/validate_checkpoint.py RUN_DIR [--json OUT.json]
        [--quarantine]

``--quarantine`` additionally renames torn checkpoints to
``<name>.corrupt`` (the run-service scrub), so the next auto-resume
scan never considers them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ramses_tpu.resilience import checkpoint as ckpt  # noqa: E402


def _check_shard_counts(sdir: str) -> (bool, str):
    """Deep payload-vs-manifest cross-check for one pario shard: the
    row intervals, oct counts and particle rows the shard manifest
    claims must match the arrays actually present in data.npz."""
    meta = ckpt.read_manifest_meta(sdir)
    rows = meta.get("rows") or {}
    path = os.path.join(sdir, "data.npz")
    if not os.path.isfile(path):
        return (not rows), ("" if not rows else "data.npz missing")
    try:
        z = np.load(path)
    except Exception as e:
        return False, f"data.npz unreadable: {e}"
    names = {k[:-2] for k in z.files if k.endswith("_n")}
    if names != set(rows):
        return False, (f"manifest rows name {sorted(rows)} != payload "
                       f"{sorted(names)}")
    for nm in sorted(names):
        got = []
        for k in range(int(z[f"{nm}_n"][0])):
            got.append([int(z[f"{nm}_r{k}"][0]),
                        int(len(z[f"{nm}_d{k}"]))])
        if sorted(got) != sorted([list(map(int, iv))
                                  for iv in rows[nm]]):
            return False, f"{nm}: manifest rows {rows[nm]} != {got}"
    return True, ""


def check_checkpoint(path: str) -> dict:
    """One checkpoint's verdict record."""
    name = os.path.basename(path)
    rec = {"name": name, "path": path, "verdict": "valid",
           "reason": ""}
    if not os.path.isfile(os.path.join(path, ckpt.MANIFEST_NAME)):
        rec["verdict"] = "unvalidated"
        rec["reason"] = "no manifest (pre-atomic science output)"
        return rec
    ok, reason = ckpt.validate_checkpoint(path, verify_hash=True)
    if not ok:
        rec["verdict"] = "torn"
        rec["reason"] = reason
        return rec
    # pario format 2: per-shard deep count checks
    shards = {}
    try:
        with open(os.path.join(path, ckpt.MANIFEST_NAME)) as f:
            ents = (json.load(f).get("shards") or {})
    except Exception:
        ents = {}
    for sname in sorted(ents):
        sok, sreason = _check_shard_counts(os.path.join(path, sname))
        shards[sname] = {"ok": bool(sok), "reason": sreason}
        if not sok:
            rec["verdict"] = "torn"
            rec["reason"] = f"{sname}: {sreason}"
    if shards:
        rec["shards"] = shards
    return rec


def scrub(base: str, quarantine: bool = False) -> dict:
    names = sorted(
        n for n in (os.listdir(base) if os.path.isdir(base) else [])
        if os.path.isdir(os.path.join(base, n))
        and any(n.startswith(p) and n[len(p):].isdigit()
                for p in ckpt.CHECKPOINT_PREFIXES))
    res = {"base": os.path.abspath(base), "checkpoints": [],
           "n_valid": 0, "n_torn": 0, "n_unvalidated": 0}
    for n in names:
        rec = check_checkpoint(os.path.join(base, n))
        if rec["verdict"] == "torn" and quarantine:
            dst = os.path.join(base, n) + ".corrupt"
            os.replace(os.path.join(base, n), dst)
            rec["quarantined"] = dst
        res["checkpoints"].append(rec)
        key = {"valid": "n_valid", "torn": "n_torn",
               "unvalidated": "n_unvalidated"}[rec["verdict"]]
        res[key] += 1
    return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="offline checkpoint scrubber (full-hash + shard "
                    "count verification)")
    ap.add_argument("run_dir")
    ap.add_argument("--json", default=None,
                    help="summary JSON path (default VALIDATE_JSON "
                         "env or VALIDATE_CKPT.json)")
    ap.add_argument("--quarantine", action="store_true",
                    help="rename torn checkpoints to <name>.corrupt")
    args = ap.parse_args(argv)
    res = scrub(args.run_dir, quarantine=args.quarantine)
    for rec in res["checkpoints"]:
        mark = {"valid": "ok  ", "torn": "TORN",
                "unvalidated": "??  "}[rec["verdict"]]
        extra = f"  ({rec['reason']})" if rec["reason"] else ""
        print(f" {mark} {rec['name']}{extra}")
    print(f" {res['n_valid']} valid, {res['n_torn']} torn, "
          f"{res['n_unvalidated']} unvalidated under {res['base']}")
    out = args.json or os.environ.get("VALIDATE_JSON",
                                      "VALIDATE_CKPT.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=1, default=str)
    print(f" wrote {out}")
    return 1 if res["n_torn"] else 0


if __name__ == "__main__":
    sys.exit(main())
