"""Physical boundary conditions as ghost-cell padding.

The reference realizes boundaries as ghost *regions* of octs filled by
``make_boundary_hydro`` (``amr/physical_boundaries.f90``,
``hydro/hydro_boundary.f90``) with integer codes from &BOUNDARY_PARAMS
(``amr/amr_parameters.f90:313-330``): 0 periodic (absence of a region),
1 reflecting, 2 outflow (zero-gradient), 3 imposed inflow.  Here each
(dimension, side) gets a :class:`FaceBC`, and :func:`pad` materializes the
ghost zones by slicing/flipping/broadcasting — dim-by-dim so corner ghosts
compose, mirroring the region-ordered fill of the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from ramses_tpu.config import Params
from ramses_tpu.hydro.core import HydroStatic

PERIODIC, REFLECTING, OUTFLOW, INFLOW = 0, 1, 2, 3


@dataclass(frozen=True)
class FaceBC:
    kind: int = PERIODIC
    # imposed primitive values for INFLOW: (d, vel..., P)
    values: Tuple[float, ...] = ()


@dataclass(frozen=True)
class BoundarySpec:
    """Per-(dim, side) boundary kinds; faces[d] = (low, high)."""
    faces: Tuple[Tuple[FaceBC, FaceBC], ...]

    @classmethod
    def periodic(cls, ndim: int) -> "BoundarySpec":
        f = FaceBC()
        return cls(faces=tuple((f, f) for _ in range(ndim)))

    @classmethod
    def from_params(cls, p: Params) -> "BoundarySpec":
        b = p.boundary
        faces: List[List[FaceBC]] = [[FaceBC(), FaceBC()]
                                     for _ in range(p.ndim)]
        mins = [b.ibound_min, b.jbound_min, b.kbound_min]
        maxs = [b.ibound_max, b.jbound_max, b.kbound_max]
        for k in range(b.nboundary):
            btype = int(b.bound_type[k])
            # reference codes: 1 reflecting, 2 outflow, 3 inflow;
            # also direction-specific 1x/2x codes collapse the same way
            kind = {1: REFLECTING, 2: OUTFLOW, 3: INFLOW}.get(btype % 10,
                                                              OUTFLOW)
            vals = (float(b.d_bound[k]),
                    *[float(v) for v in
                      (b.u_bound[k], b.v_bound[k], b.w_bound[k])[:p.ndim]],
                    float(b.p_bound[k]))
            for d in range(p.ndim):
                lo, hi = int(mins[d][k]), int(maxs[d][k])
                if lo == hi == -1:
                    faces[d][0] = FaceBC(kind, vals if kind == INFLOW else ())
                elif lo == hi == +1:
                    faces[d][1] = FaceBC(kind, vals if kind == INFLOW else ())
        return cls(faces=tuple(tuple(fs) for fs in faces))


def _inflow_state(bc: FaceBC, cfg: HydroStatic, dtype):
    """Imposed conservative state vector from primitive boundary values."""
    vals = bc.values
    r = max(vals[0], cfg.smallr)
    vels = list(vals[1:1 + cfg.ndim])
    p = vals[1 + cfg.ndim]
    u = [r] + [r * v for v in vels]
    u.append(p / (cfg.gamma - 1.0) + 0.5 * r * sum(v * v for v in vels))
    u += [0.0] * (cfg.nener + cfg.npassive)
    return jnp.asarray(np.array(u, dtype=np.float64), dtype=dtype)


def _prims_to_cons_block(vals, cfg: HydroStatic, shape, dtype):
    """Ghost block [nvar, *shape] from primitive values that may be
    scalars or per-cell arrays (position-dependent ``boundana``)."""
    r = jnp.maximum(jnp.broadcast_to(jnp.asarray(vals[0], dtype), shape),
                    cfg.smallr)
    vels = [jnp.broadcast_to(jnp.asarray(v, dtype), shape)
            for v in vals[1:1 + cfg.ndim]]
    p = jnp.broadcast_to(jnp.asarray(vals[1 + cfg.ndim], dtype), shape)
    rows = [r] + [r * v for v in vels]
    rows.append(p / (cfg.gamma - 1.0)
                + 0.5 * r * sum(v * v for v in vels))
    rows += [jnp.zeros(shape, dtype)] * (cfg.nener + cfg.npassive)
    return jnp.stack(rows)


def pad(u, spec: BoundarySpec, cfg: HydroStatic, ng: int = 2,
        dx: float = None):
    """Pad an active [nvar, *spatial] grid with ``ng`` ghost cells/side.

    ``dx``: cell size — enables POSITION-DEPENDENT inflow profiles:
    a ``boundana(d, side, cfg, x=...)`` patch hook receives the ghost
    block's cell-centre coordinate arrays (``hydro/boundana.f90:45``
    computes per-cell boundary states the same way) and may return
    per-cell primitive arrays instead of constants."""
    from ramses_tpu import patch
    boundana = patch.hook("boundana")
    for d in range(cfg.ndim):
        ax = u.ndim - cfg.ndim + d
        lo_bc, hi_bc = spec.faces[d]
        n = u.shape[ax]

        def take(start, stop, step=1):
            idx = [slice(None)] * u.ndim
            idx[ax] = slice(start, stop, step)
            return u[tuple(idx)]

        def ghost(bc: FaceBC, side: int):
            if bc.kind == PERIODIC:
                return take(n - ng, n) if side == 0 else take(0, ng)
            if bc.kind == REFLECTING:
                g = take(0, ng) if side == 0 else take(n - ng, n)
                g = jnp.flip(g, axis=ax)
                # negate normal momentum
                sign = np.ones((cfg.nvar,), dtype=np.float64)
                sign[1 + d] = -1.0
                shape = [1] * u.ndim
                shape[0] = cfg.nvar
                return g * jnp.asarray(sign, u.dtype).reshape(shape)
            if bc.kind == OUTFLOW:
                edge = take(0, 1) if side == 0 else take(n - 1, n)
                reps = [1] * u.ndim
                reps[ax] = ng
                return jnp.tile(edge, reps)
            # INFLOW
            tshape = list(u.shape)
            tshape[ax] = ng
            if boundana is not None:
                import inspect
                takes_x = "x" in inspect.signature(boundana).parameters
                if takes_x and dx is not None:
                    # ghost-cell centre coordinates per spatial dim
                    # (spatial axes only — drop the leading nvar axis)
                    sshape = tuple(tshape[u.ndim - cfg.ndim:])
                    coords = []
                    for dd in range(cfg.ndim):
                        ncells = sshape[dd]
                        if dd == d:
                            i0 = -ng if side == 0 else n
                            idxs = jnp.arange(i0, i0 + ng)
                        else:
                            # dims < d were already padded by this
                            # loop: index 0 sits at -(ng-0.5)*dx
                            off = ng if dd < d else 0
                            idxs = jnp.arange(ncells) - off
                        shape1 = [1] * cfg.ndim
                        shape1[dd] = -1
                        coords.append(
                            jnp.broadcast_to(
                                ((idxs + 0.5) * dx).astype(u.dtype)
                                .reshape(shape1), sshape))
                    vals = boundana(d, side, cfg, x=tuple(coords))
                    return _prims_to_cons_block(
                        vals, cfg, sshape, u.dtype)
                if takes_x and dx is None:
                    raise ValueError(
                        "position-aware boundana hook needs pad(..., "
                        "dx=...); this caller provides no geometry")
                vals = tuple(float(v) for v in boundana(d, side, cfg))
                bc = FaceBC(INFLOW, vals)
            state = _inflow_state(bc, cfg, u.dtype)
            shape = [1] * u.ndim
            shape[0] = cfg.nvar
            g = state.reshape(shape)
            return jnp.broadcast_to(g.astype(u.dtype), tshape)

        u = jnp.concatenate([ghost(lo_bc, 0), u, ghost(hi_bc, 1)], axis=ax)
    return u


def unpad(u, ndim: int, ng: int = 2):
    idx = [slice(None)] * u.ndim
    for d in range(ndim):
        idx[u.ndim - ndim + d] = slice(ng, u.shape[u.ndim - ndim + d] - ng)
    return u[tuple(idx)]
