"""Per-job span timeline from one trace id's artifacts.

Joins everything a ``trace_id`` (ramses_tpu/obs/trace) was stamped
into — the queue record (submit/claim/finish times, failure_log), the
job's telemetry JSONL (attempt headers, chunk cadence, resilience and
profile events) and its checkpoint manifests — into one markdown
timeline: queue wait, per-attempt chunk spans (the first chunk carries
the compile), hang/requeue/stale point events, quarantines, profile
captures.  Stdlib-only so CI and jax-free consoles can run it.

Usage::

    python tools/trace_report.py QUEUE_DIR JOB_ID [-o REPORT.md]
    python tools/trace_report.py --jsonl RUN.jsonl [--record REC.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

BAR_WIDTH = 50


def _load_jsonl(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def _find_record(queue_dir: str, job_id: str
                 ) -> Optional[Dict[str, Any]]:
    for state in ("queued", "running", "done", "failed"):
        path = os.path.join(queue_dir, state, job_id + ".json")
        if os.path.isfile(path):
            try:
                with open(path) as f:
                    rec = json.load(f)
                rec["_state"] = state
                return rec
            except (OSError, ValueError):
                return None
    return None


def _manifest_traces(rdir: str) -> List[Tuple[str, str]]:
    """``[(checkpoint_name, trace_id), ...]`` from manifest metas."""
    out: List[Tuple[str, str]] = []
    try:
        names = sorted(os.listdir(rdir))
    except OSError:
        return out
    for name in names:
        mpath = os.path.join(rdir, name, "manifest.json")
        if not os.path.isfile(mpath):
            continue
        try:
            with open(mpath) as f:
                meta = dict(json.load(f).get("meta") or {})
        except (OSError, ValueError):
            continue
        out.append((name, str(meta.get("trace_id", ""))))
    return out


def build_spans(record: Optional[Dict[str, Any]],
                recs: List[Dict[str, Any]]
                ) -> Tuple[List[Dict[str, Any]],
                           List[Dict[str, Any]], float]:
    """(spans, point_events, t0_unix).  Spans/events carry start/dur
    (or t) in seconds relative to t0 — the submit time when known,
    else the first telemetry header."""
    headers = [r for r in recs if r.get("kind") == "run_header"]
    t0 = None
    if record and record.get("submitted_unix"):
        t0 = float(record["submitted_unix"])
    elif headers:
        t0 = float(headers[0].get("time_unix") or 0.0)
    if not t0:
        t0 = 0.0
    spans: List[Dict[str, Any]] = []
    points: List[Dict[str, Any]] = []

    if record:
        sub = float(record.get("submitted_unix") or 0.0)
        claimed = float(record.get("claimed_unix") or 0.0)
        if sub and claimed:
            spans.append({"label": "queue wait", "start": sub - t0,
                          "dur": max(0.0, claimed - sub)})
        fin = float(record.get("finished_unix") or 0.0)
        if claimed and fin:
            spans.append({"label": f"claimed -> {record.get('_state', 'finished')}",
                          "start": claimed - t0,
                          "dur": max(0.0, fin - claimed)})
        for entry in record.get("failure_log") or []:
            tu = float(entry.get("time_unix") or 0.0)
            if tu:
                points.append({"label": f"{entry.get('stage', '?')} "
                                        f"(attempt {entry.get('attempt')})",
                               "t": tu - t0})

    # attempts = header-delimited segments of the (append-mode) JSONL;
    # chunk spans come from the cumulative engine wall_s each
    # ensemble_chunk carries
    attempt = 0
    head_t = None
    prev_wall = 0.0
    for r in recs:
        kind = r.get("kind")
        if kind == "run_header":
            attempt += 1
            head_t = float(r.get("time_unix") or 0.0)
            prev_wall = 0.0
            continue
        if head_t is None:
            continue
        if kind == "ensemble_chunk":
            wall = float(r.get("wall_s") or 0.0)
            dur = max(0.0, wall - prev_wall)
            label = (f"a{attempt} chunk -> nstep "
                     f"{r.get('nstep_max', '?')}")
            if prev_wall == 0.0:
                label += " (incl. compile)"
            spans.append({"label": label,
                          "start": head_t - t0 + prev_wall,
                          "dur": dur})
            prev_wall = wall
        elif kind in ("resume", "rollback", "hang", "fault",
                      "quarantine", "profile_start",
                      "profile_captured", "ensemble_done",
                      "job_summary"):
            points.append({"label": f"a{attempt} {kind}",
                           "t": head_t - t0 + prev_wall})
    return spans, points, t0


def _bar(start: float, dur: float, total: float) -> str:
    if total <= 0.0:
        return ""
    a = int(round(BAR_WIDTH * max(0.0, start) / total))
    b = max(1, int(round(BAR_WIDTH * dur / total)))
    return "." * min(a, BAR_WIDTH - 1) \
        + "#" * min(b, BAR_WIDTH - min(a, BAR_WIDTH - 1))


def render(record: Optional[Dict[str, Any]],
           recs: List[Dict[str, Any]],
           manifests: List[Tuple[str, str]],
           source: str = "") -> str:
    spans, points, _t0 = build_spans(record, recs)
    trace_rec = str((record or {}).get("trace_id", ""))
    trace_tel = next((str(r.get("trace_id")) for r in recs
                      if r.get("trace_id")), "")
    trace_id = trace_rec or trace_tel

    out = ["# Trace report", ""]
    if source:
        out.append(f"Source: `{source}`")
        out.append("")
    out.append(f"- trace_id: `{trace_id or '(unstamped)'}`")
    if record:
        out.append(f"- job: `{record.get('id', '?')}` "
                   f"[{record.get('_state', '?')}] "
                   f"attempts={record.get('attempts', 0)} "
                   f"worker=`{record.get('worker', '')}`")
    # continuity audit: every artifact that carries a trace id must
    # carry THE id — a mismatch means a results dir was reused or a
    # worker dropped the binding
    sources = {"record": trace_rec, "telemetry": trace_tel}
    for name, tid in manifests:
        sources[f"manifest:{name}"] = tid
    stamped = {k: v for k, v in sources.items() if v}
    distinct = set(stamped.values())
    if len(distinct) > 1:
        out.append(f"- **TRACE MISMATCH** across {sorted(stamped)}: "
                   f"{sorted(distinct)}")
    elif stamped:
        out.append(f"- continuity: one id across "
                   f"{len(stamped)} source(s) "
                   f"({', '.join(sorted(stamped))})")
    out.append("")

    if spans:
        end = max(s["start"] + s["dur"] for s in spans)
        out.append("## Timeline")
        out.append("")
        out.append("| span | start [s] | dur [s] | |")
        out.append("|---|---|---|---|")
        for s in sorted(spans, key=lambda s: s["start"]):
            out.append(f"| {s['label']} | {s['start']:.3f} "
                       f"| {s['dur']:.3f} "
                       f"| `{_bar(s['start'], s['dur'], end)}` |")
        out.append("")
    if points:
        out.append("## Events")
        out.append("")
        for p in sorted(points, key=lambda p: p["t"]):
            out.append(f"- t={p['t']:.3f}s {p['label']}")
        out.append("")
    if not spans and not points:
        out.append("(no spans — job not yet claimed, or telemetry "
                   "missing)")
        out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("queue_dir", nargs="?", default=None,
                    help="queue directory (with JOB_ID)")
    ap.add_argument("job_id", nargs="?", default=None)
    ap.add_argument("--jsonl", default=None,
                    help="render a telemetry JSONL directly")
    ap.add_argument("--record", default=None,
                    help="with --jsonl: the job record JSON")
    ap.add_argument("-o", "--out", default=None)
    args = ap.parse_args(argv)

    record = None
    manifests: List[Tuple[str, str]] = []
    if args.jsonl:
        recs = _load_jsonl(args.jsonl)
        source = args.jsonl
        if args.record:
            try:
                with open(args.record) as f:
                    record = json.load(f)
            except (OSError, ValueError) as e:
                raise SystemExit(f"{args.record}: {e}")
    else:
        if not (args.queue_dir and args.job_id):
            ap.error("QUEUE_DIR JOB_ID (or --jsonl) required")
        record = _find_record(args.queue_dir, args.job_id)
        if record is None:
            raise SystemExit(f"{args.queue_dir}: no job {args.job_id}")
        rdir = os.path.join(args.queue_dir, "results", args.job_id)
        recs = _load_jsonl(os.path.join(rdir, "telemetry.jsonl"))
        manifests = _manifest_traces(rdir)
        source = f"{args.queue_dir} :: {args.job_id}"
    md = render(record, recs, manifests, source=source)
    if args.out:
        with open(args.out, "w") as f:
            f.write(md + "\n")
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(md + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
