"""``docs/params.md`` freshness: the generated namelist-parameter
reference must match what ``tools/gen_params_doc.py`` renders from the
current ``config.py`` — a config change without a doc regen fails here
(and in the CI ``--check`` step) instead of rotting silently."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_gen():
    spec = importlib.util.spec_from_file_location(
        "gen_params_doc", os.path.join(REPO, "tools",
                                       "gen_params_doc.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_params_doc_fresh():
    gen = _load_gen()
    with open(os.path.join(REPO, "docs", "params.md")) as f:
        cur = f.read()
    assert cur == gen.render(), (
        "docs/params.md is stale — run `python tools/gen_params_doc.py`")


def test_params_doc_covers_every_group_and_key():
    """Structural pin: one section per _GROUP_MAP group, one row per
    dataclass field — including keys added this PR."""
    import dataclasses

    from ramses_tpu import config as cfg

    gen = _load_gen()
    text = gen.render()
    p = cfg.Params()
    for gname, attr in cfg._GROUP_MAP.items():
        assert f"## &{gname.upper()}" in text, gname
        for fld in dataclasses.fields(type(getattr(p, attr))):
            assert f"| `{fld.name}` |" in text, (gname, fld.name)
    for key in ("compile_deadline_s", "step_deadline_s",
                "io_deadline_s", "savegadget"):
        assert f"| `{key}` |" in text, key


def test_params_doc_check_mode(tmp_path, capsys, monkeypatch):
    """--check exits 0 on fresh, 1 on stale."""
    gen = _load_gen()
    doc = tmp_path / "params.md"
    doc.write_text(gen.render())
    monkeypatch.setattr(gen, "DOC_PATH", str(doc))
    assert gen.main(["--check"]) == 0
    doc.write_text("stale\n")
    assert gen.main(["--check"]) == 1
