"""Explicit shard_map+ppermute halo backend vs the global-view path.

The reference's ``make_virtual_fine`` halo exchange (``amr/
virtual_boundaries.f90:373-533``) has two TPU formulations here: the
GSPMD global-view array (compiler-inserted collectives) and the
explicit slab pipeline (``parallel/halo.py``).  Both must produce the
SAME trajectory as the single-device stepper — the decomposition-
invariance requirement (SURVEY.md §2.12 P2, ``tests/run_test_suite.sh``
multi-rank aggregate trick).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from ramses_tpu.config import params_from_string
from ramses_tpu.driver import Simulation
from ramses_tpu.grid.uniform import run_steps
from ramses_tpu.parallel.halo import make_halo_mesh, run_steps_halo


def _params(lvl, ndim):
    txt = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "/",
        "&AMR_PARAMS", f"levelmin={lvl}", f"levelmax={lvl}",
        "boxlen=1.0", "/",
        "&INIT_PARAMS", "nregion=2",
        "region_type(1)='square'", "region_type(2)='square'",
        "x_center=0.5,0.5", "y_center=0.5,0.5", "z_center=0.5,0.5",
        "length_x=10.0,0.12", "length_y=10.0,0.12",
        "length_z=10.0,0.12", "exp_region=10.0,2.0",
        "d_region=1.0,4.0", "p_region=1e-2,1.0", "/",
        "&HYDRO_PARAMS", "riemann='hllc'", "courant_factor=0.8", "/",
    ])
    return params_from_string(txt, ndim=ndim)


@pytest.mark.skipif(jax.device_count() < 8,
                    reason="needs the 8-device mesh")
@pytest.mark.parametrize("ndim,lvl", [(2, 5), (3, 4)])
def test_halo_backend_matches_single_device(ndim, lvl):
    """8-device explicit-halo trajectory == single-device trajectory,
    bitwise (f64: the slab exchange is pure data movement and the CFL
    pmin is an exact reduction)."""
    sim = Simulation(_params(lvl, ndim), dtype=jnp.float64)
    u0 = sim.state.u
    t0 = jnp.asarray(0.0, jnp.float64)
    tend = jnp.asarray(1e9, jnp.float64)
    nsteps = 6

    u_ref, t_ref, n_ref = run_steps(sim.grid, u0, t0, tend, nsteps)

    mesh = make_halo_mesh()
    assert mesh.shape["hx"] == 8          # conftest's virtual mesh
    u_h, t_h, n_h = run_steps_halo(sim.grid, mesh, u0, t0, tend, nsteps)

    assert int(n_h) == int(n_ref) == nsteps
    assert float(t_h) == float(t_ref)
    np.testing.assert_array_equal(np.asarray(u_h), np.asarray(u_ref))


def test_halo_backend_rejects_unsupported():
    p = _params(4, 2)
    p.boundary.nboundary = 2
    p.boundary.bound_type = [2, 2]
    p.boundary.ibound_min = [-1, 1]
    p.boundary.ibound_max = [-1, 1]
    p.boundary.jbound_min = [0, 0]
    p.boundary.jbound_max = [0, 0]
    p.boundary.d_bound = [0.0, 0.0]
    p.boundary.u_bound = [0.0, 0.0]
    p.boundary.v_bound = [0.0, 0.0]
    p.boundary.w_bound = [0.0, 0.0]
    p.boundary.p_bound = [0.0, 0.0]
    sim = Simulation(p, dtype=jnp.float64)
    mesh = make_halo_mesh()
    with pytest.raises(NotImplementedError):
        run_steps_halo(sim.grid, mesh, sim.state.u, 0.0, 1.0, 2)
