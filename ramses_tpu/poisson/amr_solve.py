"""Per-level Poisson solve on the AMR hierarchy.

The ``multigrid_fine``/``phi_fine_cg`` capability (SURVEY.md §3.3):
levels are solved coarse→fine with a one-way interface — each level's
solve sees Dirichlet boundary values interpolated from the coarser φ
(``make_fine_bc_rhs``), exactly the reference's masked level solve.  The
base level is complete, so its solve is the exact FFT inversion; finer
levels run preconditioned-free CG (the reference's own fallback,
``amr/amr_step.f90:250-258``) with matvec = one gather over the
face-neighbour index map.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _ext(phi, ghosts):
    zero = jnp.zeros((1,), phi.dtype)
    return jnp.concatenate([phi, ghosts, zero])


def laplacian(phi, ghosts, nb, dx, valid, ndim: int):
    """7-point Laplacian over the face-neighbour map; zero on pad rows."""
    ext = _ext(phi, ghosts)
    s = jnp.zeros_like(phi)
    for d in range(ndim):
        s = s + ext[nb[:, d, 0]] + ext[nb[:, d, 1]]
    lap = (s - 2.0 * ndim * phi) / dx ** 2
    return jnp.where(valid, lap, 0.0)


@partial(jax.jit, static_argnames=("ndim", "iters"))
def cg_level(rhs, ghosts, nb, dx, valid, ndim: int, iters: int = 200,
             phi0=None):
    """CG solve of Δφ = rhs with fixed Dirichlet ghosts.

    The affine split: A(φ) ≡ lap(φ, 0); b ≡ rhs − lap(0, ghosts).  A is
    symmetric negative definite on the masked cells; CG runs on −A.
    """
    zero_g = jnp.zeros_like(ghosts)
    b = jnp.where(valid,
                  rhs - laplacian(jnp.zeros_like(rhs), ghosts, nb, dx,
                                  valid, ndim), 0.0)

    def A(x):
        return -laplacian(x, zero_g, nb, dx, valid, ndim)

    x = (phi0 if phi0 is not None else jnp.zeros_like(rhs))
    r = jnp.where(valid, -b - A(x), 0.0)
    p = r
    rs = jnp.sum(r * r)

    def body(i, state):
        x, r, p, rs = state
        Ap = A(p)
        denom = jnp.sum(p * Ap)
        alpha = jnp.where(denom != 0.0, rs / denom, 0.0)
        x = x + alpha * p
        r = r - alpha * Ap
        rs_new = jnp.sum(r * r)
        beta = jnp.where(rs != 0.0, rs_new / rs, 0.0)
        p = r + beta * p
        return x, r, p, rs_new

    x, r, p, rs = jax.lax.fori_loop(0, iters, body, (x, r, p, rs))
    return jnp.where(valid, x, 0.0)


def _lat_apply(e, lat_nb, dxj, ndim: int):
    """−Δe on a masked lattice (sentinel row = outside the mask =
    Dirichlet 0 for the error equation)."""
    ext = jnp.concatenate([e, jnp.zeros((1,), e.dtype)])
    s = jnp.zeros_like(e)
    for d in range(ndim):
        s = s + ext[lat_nb[:, d, 0]] + ext[lat_nb[:, d, 1]]
    return -(s - 2.0 * ndim * e) / (dxj * dxj)


def _lat_jacobi(e, r, lat_nb, dxj, ndim: int, nu: int):
    """``nu`` damped-Jacobi sweeps of −Δe = r on a masked lattice."""
    diag = 2.0 * ndim / (dxj * dxj)
    for _ in range(nu):
        e = e + 0.6 * (r - _lat_apply(e, lat_nb, dxj, ndim)) / diag
    return e


@partial(jax.jit, static_argnames=("ndim", "iters", "nu"))
def pcg_level(rhs, ghosts, nb, oct_nb, dx, valid, ndim: int,
              tol: float = 1e-4, iters: int = 200, nu: int = 4,
              phi0=None, mg=()):
    """Preconditioned CG with residual-targeted termination.

    The reference solves each AMR level with masked multigrid to
    ``epsilon`` (``poisson/multigrid_fine_commons.f90:25-305``) or CG
    above ``cg_levelmin``.  Here: CG on the masked level system,
    preconditioned by the masked-multigrid ladder —
    ``M^-1 r = w_f * D^-1 r + P V(P^T r)`` where V is a symmetric
    V-cycle (damped-Jacobi smoothing, piecewise-constant transfer)
    over the coarsened oct lattices of the SAME masked domain
    (``mg``; :func:`ramses_tpu.amr.maps.build_mg_lattices`) — the
    ``multigrid_fine_fine`` level ladder as a preconditioner, which
    keeps the epsilon-targeted CG outer loop and its live iteration
    count (the multigrid-iters metric).  Every ingredient is a
    symmetric positive operator, so CG theory holds.

    Returns (phi, niter).
    """
    ttd = 2 ** ndim
    zero_g = jnp.zeros_like(ghosts)
    b = jnp.where(valid,
                  rhs - laplacian(jnp.zeros_like(rhs), ghosts, nb, dx,
                                  valid, ndim), 0.0)

    def A(x):
        return -laplacian(x, zero_g, nb, dx, valid, ndim)

    def vcycle(j, rj):
        """Symmetric V-cycle on lattice depth j (0 = oct lattice)."""
        dxj = dx * (2.0 ** (j + 1))
        lat_nb = oct_nb if j == 0 else mg[j - 1][0]
        ej = _lat_jacobi(jnp.zeros_like(rj), rj, lat_nb, dxj, ndim, nu)
        if j < len(mg):
            par = mg[j][1]               # depth j -> j+1 parent index
            n_next = mg[j][0].shape[0]
            resid = rj - _lat_apply(ej, lat_nb, dxj, ndim)
            r_next = jnp.zeros((n_next,), rj.dtype).at[par].add(
                resid[:par.shape[0]], mode="drop") / ttd
            e_next = vcycle(j + 1, r_next)
            ext = jnp.concatenate([e_next, jnp.zeros((1,),
                                                     e_next.dtype)])
            ej = ej + ext[par[:rj.shape[0]]]
            ej = _lat_jacobi(ej, rj, lat_nb, dxj, ndim, nu)
        return ej

    def Minv(r):
        # restrict cells -> oct lattice (adjoint of repeat), V-cycle
        # down the masked ladder, prolong back
        rc = r.reshape(-1, ttd).sum(axis=1) / ttd        # [noct_pad]
        ec = vcycle(0, rc)
        e = jnp.repeat(ec, ttd)
        # fine half: damped diagonal
        diag_f = 2.0 * ndim / (dx * dx)
        e = e + 0.6 * r / diag_f
        return jnp.where(valid, e, 0.0)

    x = (phi0 if phi0 is not None else jnp.zeros_like(rhs))
    r = jnp.where(valid, -b - A(x), 0.0)
    z = Minv(r)
    p = z
    rz = jnp.sum(r * z)
    # epsilon is relative to the SYSTEM rhs (the reference's multigrid
    # convergence norm), not to the warm-start residual — else a good
    # phi0 would make the target unreachably strict
    bb = jnp.sum(b * b)
    cut = jnp.asarray(tol, rhs.dtype) ** 2 * jnp.maximum(
        bb, jnp.finfo(rhs.dtype).tiny)

    def body(i, state):
        x, r, p, rz, niter = state
        rr = jnp.sum(r * r)
        live = rr > cut
        Ap = A(p)
        denom = jnp.sum(p * Ap)
        alpha = jnp.where(live & (denom != 0.0),
                          rz / jnp.where(denom == 0.0, 1.0, denom), 0.0)
        x = x + alpha * p
        r_new = r - alpha * Ap
        z_new = Minv(r_new)
        rz_new = jnp.sum(r_new * z_new)
        beta = jnp.where(live & (rz != 0.0),
                         rz_new / jnp.where(rz == 0.0, 1.0, rz), 0.0)
        p = jnp.where(live, z_new + beta * p, p)
        return (x, jnp.where(live, r_new, r), p,
                jnp.where(live, rz_new, rz), niter + live)

    x, r, p, rz, niter = jax.lax.fori_loop(
        0, iters, body, (x, r, p, rz, jnp.array(0, jnp.int32)))
    return jnp.where(valid, x, 0.0), niter


@partial(jax.jit, static_argnames=("ndim",))
def grad_phi(phi, ghosts, nb, dx, valid, ndim: int):
    """Central-difference force f = −∇φ, [ncell_pad, ndim]
    (``force_fine``'s 5-point gradient)."""
    ext = _ext(phi, ghosts)
    comps = []
    for d in range(ndim):
        g = -(ext[nb[:, d, 1]] - ext[nb[:, d, 0]]) / (2.0 * dx)
        comps.append(jnp.where(valid, g, 0.0))
    return jnp.stack(comps, axis=1)


@partial(jax.jit, static_argnames=("ndim",))
def grad_dense(phi_dense, dx, ndim: int):
    """f = −∇φ on a dense periodic grid, 4th-order 5-point stencil
    (``force_fine``'s gradient, the same operator as
    ``poisson/force.py:gradient_phi``); returns the dense grid
    ``[*shape, ndim]`` (the complete-level companion of
    :func:`grad_phi`)."""
    a = 2.0 / (3.0 * dx)
    b = 1.0 / (12.0 * dx)
    comps = []
    for d in range(ndim):
        d1 = jnp.roll(phi_dense, -1, axis=d) - jnp.roll(phi_dense, 1, axis=d)
        d2 = jnp.roll(phi_dense, -2, axis=d) - jnp.roll(phi_dense, 2, axis=d)
        comps.append(-(a * d1 - b * d2))
    return jnp.stack(comps, axis=-1)


@partial(jax.jit, static_argnames=("ndim",))
def kick_flat(u, f, dteff, ndim: int, smallr: float):
    """Gravity momentum kick on flat cells [ncell, nvar] at fixed
    internal energy (``synchro_hydro_fine``)."""
    r = jnp.maximum(u[:, 0], smallr)
    ek_old = sum(0.5 * u[:, 1 + d] ** 2 for d in range(ndim)) / r
    mom = [u[:, 1 + d] + r * f[:, d] * dteff for d in range(ndim)]
    ek_new = sum(0.5 * m * m for m in mom) / r
    e = u[:, 1 + ndim] - ek_old + ek_new
    cols = [u[:, 0:1]] + [m[:, None] for m in mom] + [e[:, None]]
    if u.shape[1] > ndim + 2:
        cols.append(u[:, ndim + 2:])
    return jnp.concatenate(cols, axis=1)
