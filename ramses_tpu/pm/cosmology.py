"""Cosmology: Friedmann tables and supercomoving-unit scaffolding.

Reference: ``amr/init_time.f90`` — ``init_cosmo`` (``:414``) and
``friedman`` (``:756-855``).  The reference integrates the Friedmann
equation backwards from a=1 with adaptive RK2 and stores look-up tables
``axp_frw/hexp_frw/tau_frw/t_frw``; time stepping then advances the
conformal time ``tau`` (code time) and interpolates ``aexp``.

Conventions (comment block ``init_time.f90:764-773``):
  - a = 1 today; tau (conformal, da/dtau convention below) and t
    (proper look-back) are 0 today, both in units of 1/H0
  - da/dtau = sqrt(a^3 (Om + Ol a^3 + Ok a))       (``dadtau:857-866``)
  - da/dt   = sqrt((Om + Ol a^3 + Ok a) / a)
  - hexp = (1/a) da/dtau

Here the tables are built by direct quadrature on a fine log-spaced grid
(vectorized, deterministic) instead of the sequential RK2 — same curves,
no 1e6-step Python loop.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax.numpy as jnp
import numpy as np


def dadtau(a, om, ov, ok):
    return np.sqrt(a ** 3 * (om + ov * a ** 3 + ok * a))


def dadt(a, om, ov, ok):
    return np.sqrt((om + ov * a ** 3 + ok * a) / a)


def friedman(om: float, ov: float, ok: float, aexp_min: float,
             ntable: int = 1000):
    """Look-up tables (a, hexp, tau, t, chi) from a=aexp_min/1.2 to 1.

    Quadrature replacement of ``friedman`` (``amr/init_time.f90:756``):
    tau(a) = -int_a^1 da'/dadtau, t(a) = -int_a^1 da'/dadt, plus the
    proper comoving distance chi(a) = int_a^1 c·da'/(a'^2 H(a')) in
    c/H0 units (the lightcone's ``coord_distance`` integrand,
    ``amr/light_cone.f90:795-804``; note a'/dadtau = 1/(a'^2 E)).
    """
    if abs(om + ov + ok - 1.0) > 1e-9:
        raise ValueError(f"Omegas must sum to 1: {om}+{ov}+{ok}")
    nfine = max(20 * ntable, 20000)
    a_fine = np.exp(np.linspace(np.log(aexp_min / 1.2), 0.0, nfine))
    inv_dtau = 1.0 / dadtau(a_fine, om, ov, ok)
    inv_dt = 1.0 / dadt(a_fine, om, ov, ok)
    inv_chi = a_fine * inv_dtau            # 1/(a^2 E(a)) in 1/H0 units
    # cumulative trapezoid from a=1 downward (negative times in the past)
    da = np.diff(a_fine)

    def cum(f):
        return np.concatenate([[0.0],
                               np.cumsum(0.5 * da * (f[1:] + f[:-1]))])

    tau_f = cum(inv_dtau)
    t_f = cum(inv_dt)
    chi_f = cum(inv_chi)
    tau_f = tau_f - tau_f[-1]   # tau(1) = 0, negative in the past
    t_f = t_f - t_f[-1]
    chi_f = chi_f[-1] - chi_f   # chi(1) = 0, POSITIVE in the past
    # subsample to ntable+1 entries (reference keeps 0:ntable)
    idx = np.linspace(0, nfine - 1, ntable + 1).round().astype(int)
    a_t = a_fine[idx]
    return (a_t, dadtau(a_t, om, ov, ok) / a_t, tau_f[idx], t_f[idx],
            chi_f[idx])


@dataclass(frozen=True)
class Cosmology:
    """Flat(ish) FRW background + supercomoving unit scales.

    Code units follow the reference (``amr/units.f90`` with cosmo):
    scale_d = Om*rhocrit(h)*h^2/a^3, scale_t = a^2/H0,
    scale_l = a * boxlen_ini Mpc / h.
    """
    omega_m: float = 1.0
    omega_l: float = 0.0
    omega_k: float = 0.0
    omega_b: float = 0.045
    h0: float = 70.0               # km/s/Mpc
    aexp_ini: float = 1e-2
    boxlen_ini: float = 1.0        # comoving Mpc/h
    ntable: int = 1000
    # tables (tuples for hashability; filled in __post_init__)
    axp_frw: Tuple[float, ...] = ()
    hexp_frw: Tuple[float, ...] = ()
    tau_frw: Tuple[float, ...] = ()
    t_frw: Tuple[float, ...] = ()
    chi_frw: Tuple[float, ...] = ()    # comoving distance to a=1, c/H0

    def __post_init__(self):
        if not self.axp_frw:
            a, h, tau, t, chi = friedman(self.omega_m, self.omega_l,
                                         self.omega_k, self.aexp_ini,
                                         self.ntable)
            object.__setattr__(self, "axp_frw", tuple(a))
            object.__setattr__(self, "hexp_frw", tuple(h))
            object.__setattr__(self, "tau_frw", tuple(tau))
            object.__setattr__(self, "t_frw", tuple(t))
            object.__setattr__(self, "chi_frw", tuple(chi))

    @classmethod
    def from_params(cls, p) -> "Cosmology":
        raw = (p.raw or {}).get("cosmo_params", {})
        return cls(omega_m=float(raw.get("omega_m", 1.0)),
                   omega_l=float(raw.get("omega_l", 0.0)),
                   omega_k=float(raw.get("omega_k", 0.0)),
                   omega_b=float(raw.get("omega_b", 0.045)),
                   h0=float(raw.get("h0", 70.0)),
                   aexp_ini=float(raw.get(
                       "aexp", raw.get("aexp_ini", p.init.aexp_ini
                                       if p.init.aexp_ini < 1.0
                                       else 1e-2))),
                   boxlen_ini=float(raw.get("boxlen_ini", p.amr.boxlen)))

    # --- interpolators (host or device) ------------------------------
    def aexp_of_tau(self, tau):
        return jnp.interp(tau, jnp.asarray(self.tau_frw),
                          jnp.asarray(self.axp_frw))

    def hexp_of_tau(self, tau):
        return jnp.interp(tau, jnp.asarray(self.tau_frw),
                          jnp.asarray(self.hexp_frw))

    def t_of_tau(self, tau):
        return jnp.interp(tau, jnp.asarray(self.tau_frw),
                          jnp.asarray(self.t_frw))

    def tau_of_aexp(self, aexp):
        return jnp.interp(aexp, jnp.asarray(self.axp_frw),
                          jnp.asarray(self.tau_frw))

    # --- lightcone comoving distances (box-length units) --------------
    @property
    def _chi_to_box(self) -> float:
        """c/H0 expressed in box lengths: coverH0/Lbox with
        coverH0 = 299792.458/(100·h) Mpc and Lbox = boxlen_ini/h Mpc
        (``light_cone.f90:57,791``) — h cancels."""
        return 2997.92458 / self.boxlen_ini

    def chi_of_aexp(self, aexp):
        """Proper comoving distance from aexp to today, box units."""
        return jnp.interp(aexp, jnp.asarray(self.axp_frw),
                          jnp.asarray(self.chi_frw)) * self._chi_to_box

    def aexp_of_chi(self, chi):
        """Emission epoch at comoving distance ``chi`` [box units]."""
        c = jnp.asarray(self.chi_frw[::-1]) * self._chi_to_box
        return jnp.interp(chi, c, jnp.asarray(self.axp_frw[::-1]))

    @property
    def tau_ini(self) -> float:
        return float(self.tau_of_aexp(self.aexp_ini))

    def age_of_universe(self) -> float:
        """In 1/H0 units (the reference's debug print, init_time.f90:811)."""
        return -float(self.t_frw[0])
