"""Tabulated atomic cooling/heating (the ``cooling_module`` equivalent).

Capability match for ``hydro/cooling_module.f90`` (SURVEY.md §2.2):
equilibrium H/He thermochemistry tabulated on a (log nH, log T2) grid,
UV-background photoheating, Compton cooling/heating against the CMB,
metallicity-scaled metal cooling, self-shielding boost, and the
semi-implicit stiff integrator of ``solve_cooling``
(``hydro/cooling_module.f90:478-664``) re-expressed as a batched
``lax.while_loop`` (all cells advance their private pseudo-time in
lockstep; finished lanes are masked).

The microphysics uses the standard published rate fits (Cen 1992; Katz,
Weinberg & Hernquist 1996 collisional rates and cooling functions;
power-law UV spectrum with Osterbrock cross sections; Sutherland &
Dopita-shaped metal cooling approximation) — same physics content as the
reference's tables, independently implemented.  Tables are built on the
host in numpy at startup (the ``set_table(aexp)`` pass) and shipped to the
device as constants.

Conventions: ``T2`` is T/mu in Kelvin; ``nH`` in H/cc; rates in
erg cm^3 / s so that dT2/dt = -(2X/3kB) * nH * Lambda_net.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.units import X_frac, kB

# table geometry (cooling_module.f90:40-45)
NBIN_T = 101
NBIN_N = 161
NH_MIN, NH_MAX = 1e-10, 1e6
T2_MIN, T2_MAX = 1e-2, 1e9
Y_frac = 1.0 - X_frac
VARMAX = 4.0        # per-substep relative change bound (solve_cooling)
T_CMB0 = 2.726


# ----------------------------------------------------------------------
# rate fits (Cen 1992 / KWH 1996), T in Kelvin
# ----------------------------------------------------------------------

def _T5(T):
    return np.sqrt(T / 1e5)


def rate_ci_HI(T):
    """Collisional ionization rate coefficient e + HI [cm^3/s]."""
    return 5.85e-11 * np.sqrt(T) * np.exp(-157809.1 / T) / (1 + _T5(T))


def rate_ci_HeI(T):
    return 2.38e-11 * np.sqrt(T) * np.exp(-285335.4 / T) / (1 + _T5(T))


def rate_ci_HeII(T):
    return 5.68e-12 * np.sqrt(T) * np.exp(-631515.0 / T) / (1 + _T5(T))


def rate_rec_HII(T):
    """Case-A recombination HII + e [cm^3/s]."""
    return (8.4e-11 / np.sqrt(T) * (T / 1e3) ** -0.2
            / (1 + (T / 1e6) ** 0.7))


def rate_rec_HeII(T):
    return 1.5e-10 * T ** -0.6353


def rate_rec_dielec(T):
    return (1.9e-3 * T ** -1.5 * np.exp(-470000.0 / T)
            * (1 + 0.3 * np.exp(-94000.0 / T)))


def rate_rec_HeIII(T):
    return (3.36e-10 / np.sqrt(T) * (T / 1e3) ** -0.2
            / (1 + (T / 1e6) ** 0.7))


# cooling functions [erg cm^3/s], to be multiplied by n_e * n_ion
def cool_ci_HI(T):
    return 1.27e-21 * np.sqrt(T) * np.exp(-157809.1 / T) / (1 + _T5(T))


def cool_ci_HeI(T):
    return 9.38e-22 * np.sqrt(T) * np.exp(-285335.4 / T) / (1 + _T5(T))


def cool_ci_HeII(T):
    return 4.95e-22 * np.sqrt(T) * np.exp(-631515.0 / T) / (1 + _T5(T))


def cool_ce_HI(T):
    """Collisional excitation (line) cooling."""
    return 7.50e-19 * np.exp(-118348.0 / T) / (1 + _T5(T))


def cool_ce_HeII(T):
    return 5.54e-17 * T ** -0.397 * np.exp(-473638.0 / T) / (1 + _T5(T))


def cool_rec_HII(T):
    return (8.70e-27 * np.sqrt(T) * (T / 1e3) ** -0.2
            / (1 + (T / 1e6) ** 0.7))


def cool_rec_HeII(T):
    return 1.55e-26 * T ** 0.3647


def cool_rec_dielec(T):
    return (1.24e-13 * T ** -1.5 * np.exp(-470000.0 / T)
            * (1 + 0.3 * np.exp(-94000.0 / T)))


def cool_rec_HeIII(T):
    return (3.48e-26 * np.sqrt(T) * (T / 1e3) ** -0.2
            / (1 + (T / 1e6) ** 0.7))


def cool_brems(T, nHII, nHeII, nHeIII, ne):
    gff = 1.1 + 0.34 * np.exp(-((5.5 - np.log10(T)) ** 2) / 3.0)
    return 1.42e-27 * gff * np.sqrt(T) * (nHII + nHeII + 4.0 * nHeIII) * ne


def metal_cooling_solar(T):
    """Solar-metallicity metal-line cooling [erg cm^3/s], SD93-shaped
    piecewise approximation: off below 1e4 K, peaked near 1e5.2 K, shallow
    high-T tail (the reference embeds the Courty tables here)."""
    logT = np.log10(np.maximum(T, 1.0))
    lam = np.full_like(logT, -60.0)
    # rising edge 1e4..10^5.2
    m1 = (logT >= 4.0) & (logT < 5.2)
    lam = np.where(m1, -21.7 + 1.2 * (logT - 5.2), lam)
    # peak plateau 10^5.2..10^6
    m2 = (logT >= 5.2) & (logT < 6.0)
    lam = np.where(m2, -21.7 - 0.4 * (logT - 5.2), lam)
    # decline 10^6..10^7.5, then flat tail
    m3 = (logT >= 6.0) & (logT < 7.5)
    lam = np.where(m3, -22.02 - 0.6 * (logT - 6.0), lam)
    m4 = logT >= 7.5
    lam = np.where(m4, -22.92, lam)
    return 10.0 ** lam


# ----------------------------------------------------------------------
# UV background: power-law spectrum J(nu) = J0 (nu/nu_HI)^-alpha
# ----------------------------------------------------------------------

_NU_THRESH = dict(HI=3.2880e15, HeI=5.9484e15, HeII=1.3158e16)  # Hz
_H_PLANCK = 6.6262e-27


def _sigma_HI(nu):
    x = nu / _NU_THRESH["HI"]
    return np.where(x >= 1.0, 6.30e-18 * x ** -3.0, 0.0)


def _sigma_HeI(nu):
    x = nu / _NU_THRESH["HeI"]
    return np.where(x >= 1.0,
                    7.42e-18 * (1.66 * x ** -2.05 - 0.66 * x ** -3.05), 0.0)


def _sigma_HeII(nu):
    x = nu / _NU_THRESH["HeII"]
    return np.where(x >= 1.0, 1.58e-18 * x ** -3.0, 0.0)


def uv_amplitude(aexp: float, J21: float, z_reion: float = 8.5,
                 haardt_madau: bool = False) -> float:
    """Effective J21 amplitude at this epoch: zero before reionization,
    then flat, or the HM-style (1+z)^0.73·exp decline toward z=0
    (shared by the equilibrium-cooling tables and the RT chemistry's
    homogeneous UV background, ``rt_UV_hom``)."""
    z = 1.0 / max(aexp, 1e-10) - 1.0
    if z >= z_reion:
        return 0.0
    return J21 * ((1 + z) ** 0.73 * np.exp(-((1 + z) / 9.0) ** 2)
                  if haardt_madau else 1.0)


def uv_rates(J21: float, alpha: float):
    """(photoionization [1/s], photoheating [erg/s]) per species for the
    power-law background; numerical quadrature over the spectrum."""
    out_gamma, out_heat = {}, {}
    for sp, sigma in (("HI", _sigma_HI), ("HeI", _sigma_HeI),
                      ("HeII", _sigma_HeII)):
        nu0 = _NU_THRESH[sp]
        nu = nu0 * np.logspace(0, 2.5, 400)
        Jnu = J21 * 1e-21 * (nu / _NU_THRESH["HI"]) ** (-alpha)
        integ_i = 4 * np.pi * Jnu / (_H_PLANCK * nu) * sigma(nu)
        integ_h = integ_i * _H_PLANCK * (nu - nu0)
        out_gamma[sp] = np.trapezoid(integ_i, nu)
        out_heat[sp] = np.trapezoid(integ_h, nu)
    return out_gamma, out_heat


# ----------------------------------------------------------------------
# ionization equilibrium + table build (set_table equivalent)
# ----------------------------------------------------------------------

def _equilibrium(nH, T, gamma_uv):
    """H/He ionization equilibrium (KWH96 §3): returns species densities
    (nHI, nHII, nHeI, nHeII, nHeIII, ne) for scalar-broadcastable arrays.
    Fixed-point iteration on ne."""
    nHe = 0.25 * Y_frac / X_frac * nH
    ge_HI, ge_HeI, ge_HeII = (rate_ci_HI(T), rate_ci_HeI(T),
                              rate_ci_HeII(T))
    a_HII = rate_rec_HII(T)
    a_HeII = rate_rec_HeII(T) + rate_rec_dielec(T)
    a_HeIII = rate_rec_HeIII(T)
    gg_HI = gamma_uv.get("HI", 0.0)
    gg_HeI = gamma_uv.get("HeI", 0.0)
    gg_HeII = gamma_uv.get("HeII", 0.0)

    ne = nH * 1.0
    for _ in range(100):
        ne_safe = np.maximum(ne, 1e-30 * nH)
        # hydrogen
        denom = a_HII + ge_HI + gg_HI / ne_safe
        nHI = nH * a_HII / np.maximum(denom, 1e-300)
        nHII = nH - nHI
        # helium chain
        r1 = (ge_HeI + gg_HeI / ne_safe) / a_HeII
        r2 = (ge_HeII + gg_HeII / ne_safe) / a_HeIII
        nHeI = nHe / (1.0 + r1 + r1 * r2)
        nHeII = nHeI * r1
        nHeIII = nHeII * r2
        ne_new = nHII + nHeII + 2.0 * nHeIII
        ne = 0.5 * ne + 0.5 * ne_new
    return nHI, nHII, nHeI, nHeII, nHeIII, ne


@dataclass
class CoolingTables:
    """Device-resident log10 tables over (log nH, log T2) + T-derivative
    tables for the cubic-Hermite interpolation of ``solve_cooling``."""
    log_nH: jnp.ndarray          # [NBIN_N]
    log_T2: jnp.ndarray          # [NBIN_T]
    cool: jnp.ndarray            # [NBIN_N, NBIN_T] log10 Lambda
    heat: jnp.ndarray
    cool_com: jnp.ndarray
    heat_com: jnp.ndarray
    metal: jnp.ndarray
    cool_p: jnp.ndarray          # d log10 Lambda / d log10 T2
    heat_p: jnp.ndarray
    cool_com_p: jnp.ndarray
    heat_com_p: jnp.ndarray
    metal_p: jnp.ndarray
    mu: jnp.ndarray              # mean molecular weight


def _prime(tab, dlogT):
    p = np.gradient(tab, dlogT, axis=1)
    return p


def build_tables(aexp: float = 1.0, J21: float = 0.0,
                 a_spec: float = 1.0, z_reion: float = 8.5,
                 haardt_madau: bool = False) -> CoolingTables:
    """``set_table(aexp)``: tabulate net cooling/heating at this epoch.

    ``haardt_madau`` selects a softer evolving amplitude for the UV
    background; both modes use the power-law spectral shape.
    """
    z = 1.0 / aexp - 1.0
    log_nH = np.linspace(np.log10(NH_MIN), np.log10(NH_MAX), NBIN_N)
    log_T2 = np.linspace(np.log10(T2_MIN), np.log10(T2_MAX), NBIN_T)
    nH = 10.0 ** log_nH[:, None]                     # [N, 1]
    T2 = 10.0 ** log_T2[None, :]                     # [1, T]

    J_eff = uv_amplitude(aexp, J21, z_reion, haardt_madau)
    gamma_uv, heat_uv = uv_rates(J_eff, a_spec) if J_eff > 0 else ({}, {})

    # solve T = T2 * mu self-consistently (mu depends on ionization)
    mu = np.full(nH.shape[:1] + T2.shape[1:], 1.22)
    mu = np.broadcast_to(mu, (NBIN_N, NBIN_T)).copy()
    for _ in range(10):
        T = T2 * mu
        nHI, nHII, nHeI, nHeII, nHeIII, ne = _equilibrium(nH, T, gamma_uv)
        ntot = nHI + nHII + nHeI + nHeII + nHeIII + ne
        mu_new = nH / X_frac / np.maximum(ntot, 1e-300)
        mu = 0.5 * mu + 0.5 * mu_new
    T = T2 * mu

    # cooling [erg/s/cm^3] then normalized by nH^2 → erg cm^3/s
    lam = (cool_ci_HI(T) * ne * nHI
           + cool_ci_HeI(T) * ne * nHeI
           + cool_ci_HeII(T) * ne * nHeII
           + cool_ce_HI(T) * ne * nHI
           + cool_ce_HeII(T) * ne * nHeII
           + cool_rec_HII(T) * ne * nHII
           + cool_rec_HeII(T) * ne * nHeII
           + cool_rec_dielec(T) * ne * nHeII
           + cool_rec_HeIII(T) * ne * nHeIII
           + cool_brems(T, nHII, nHeII, nHeIII, ne)) / nH ** 2

    heat = (heat_uv.get("HI", 0.0) * nHI
            + heat_uv.get("HeI", 0.0) * nHeI
            + heat_uv.get("HeII", 0.0) * nHeII) / nH ** 2
    heat = np.broadcast_to(heat, lam.shape)

    # Compton vs CMB: tabulated per (ne/nH) so the extra /nH applied in
    # the lambda sum yields rate = tab/nH * nH^2 = 5.4e-36 (1+z)^4 ne ΔT
    t_cmb = T_CMB0 * (1 + z)
    comp = 5.406e-36 * (1 + z) ** 4 * ne / nH
    cool_com = comp * np.maximum(T - t_cmb, 0.0)
    heat_com = comp * np.maximum(t_cmb - T, 0.0)

    metal = metal_cooling_solar(T) * (ne * nH / nH ** 2)

    floor = 1e-100
    dlogT = log_T2[1] - log_T2[0]

    def logt(tab):
        return np.log10(np.maximum(tab, floor))

    tabs = {}
    for name, tab in (("cool", lam), ("heat", heat),
                      ("cool_com", cool_com), ("heat_com", heat_com),
                      ("metal", metal)):
        lt = logt(tab)
        tabs[name] = lt
        tabs[name + "_p"] = _prime(lt, dlogT)

    return CoolingTables(
        log_nH=jnp.asarray(log_nH), log_T2=jnp.asarray(log_T2),
        cool=jnp.asarray(tabs["cool"]), heat=jnp.asarray(tabs["heat"]),
        cool_com=jnp.asarray(tabs["cool_com"]),
        heat_com=jnp.asarray(tabs["heat_com"]),
        metal=jnp.asarray(tabs["metal"]),
        cool_p=jnp.asarray(tabs["cool_p"]),
        heat_p=jnp.asarray(tabs["heat_p"]),
        cool_com_p=jnp.asarray(tabs["cool_com_p"]),
        heat_com_p=jnp.asarray(tabs["heat_com_p"]),
        metal_p=jnp.asarray(tabs["metal_p"]),
        mu=jnp.asarray(mu))


jax.tree_util.register_pytree_node(
    CoolingTables,
    lambda t: ((t.log_nH, t.log_T2, t.cool, t.heat, t.cool_com, t.heat_com,
                t.metal, t.cool_p, t.heat_p, t.cool_com_p, t.heat_com_p,
                t.metal_p, t.mu), None),
    lambda aux, ch: CoolingTables(*ch))


# ----------------------------------------------------------------------
# the stiff integrator (solve_cooling, cooling_module.f90:478-664)
# ----------------------------------------------------------------------

def _interp_T(tab, tab_p, i_nH, w1, w2, i_T2, yy, h):
    """Cubic Hermite in log T2 at fixed (interpolated) nH — the fa/fb/
    fprimea/fprimeb evaluation of the reference."""
    fa = tab[i_nH, i_T2] * w1 + tab[i_nH + 1, i_T2] * w2
    fb = tab[i_nH, i_T2 + 1] * w1 + tab[i_nH + 1, i_T2 + 1] * w2
    fpa = tab_p[i_nH, i_T2] * w1 + tab_p[i_nH + 1, i_T2] * w2
    fpb = tab_p[i_nH, i_T2 + 1] * w1 + tab_p[i_nH + 1, i_T2 + 1] * w2
    alpha = fpa
    beta = 3.0 * (fb - fa) / h ** 2 - (2.0 * fpa + fpb) / h
    gamma = (fpa + fpb) / h ** 2 - 2.0 * (fb - fa) / h ** 3
    val = 10.0 ** (fa + alpha * yy + beta * yy ** 2 + gamma * yy ** 3)
    dlog = alpha + 2.0 * beta * yy + 3.0 * gamma * yy ** 2
    return val, dlog


@jax.jit
def solve_cooling(tables: CoolingTables, nH, T2, zsolar, boost, dt_s):
    """Advance T2 over ``dt_s`` seconds at fixed nH.  Returns new T2.

    The reference's scheme verbatim (``:478-664``): per-cell pseudo-time
    marching with semi-implicit updates limited to VARMAX relative change,
    then linear interpolation onto the exact end time.
    """
    shape = nH.shape
    nH = nH.reshape(-1)
    T2 = T2.reshape(-1)

    def _flat(v):
        v = jnp.asarray(v, nH.dtype)
        return (v.reshape(-1) if v.ndim > 0
                else jnp.broadcast_to(v, nH.shape))

    zsolar = _flat(zsolar)
    boost = _flat(boost)

    log_nH0 = tables.log_nH[0]
    log_T20 = tables.log_T2[0]
    dlog_nH = (NBIN_N - 1) / (tables.log_nH[-1] - log_nH0)
    dlog_T2 = (NBIN_T - 1) / (tables.log_T2[-1] - log_T20)
    h = 1.0 / dlog_T2
    precoeff = 2.0 * X_frac / (3.0 * kB)

    facH = jnp.clip(jnp.log10(nH / boost), log_nH0, tables.log_nH[-1])
    i_nH = jnp.clip(((facH - log_nH0) * dlog_nH).astype(jnp.int32),
                    0, NBIN_N - 2)
    w1 = (tables.log_nH[i_nH + 1] - facH) * dlog_nH
    w2 = (facH - tables.log_nH[i_nH]) * dlog_nH

    time_max = dt_s * precoeff * nH
    wmax = 1.0 / time_max

    def rate(tau):
        facT = jnp.log10(tau)
        in_table = facT <= jnp.log10(T2_MAX)
        i_T2 = jnp.clip(((facT - log_T20) * dlog_T2).astype(jnp.int32),
                        0, NBIN_T - 2)
        yy = facT - tables.log_T2[i_T2]
        cool, cool_d = _interp_T(tables.cool, tables.cool_p, i_nH, w1, w2,
                                 i_T2, yy, h)
        heat, heat_d = _interp_T(tables.heat, tables.heat_p, i_nH, w1, w2,
                                 i_T2, yy, h)
        ccom, ccom_d = _interp_T(tables.cool_com, tables.cool_com_p, i_nH,
                                 w1, w2, i_T2, yy, h)
        hcom, hcom_d = _interp_T(tables.heat_com, tables.heat_com_p, i_nH,
                                 w1, w2, i_T2, yy, h)
        met, met_d = _interp_T(tables.metal, tables.metal_p, i_nH, w1, w2,
                               i_T2, yy, h)
        lam = cool + zsolar * met - heat + (ccom - hcom) / nH
        lam_p = (cool * cool_d + zsolar * met * met_d - heat * heat_d
                 + (ccom * ccom_d - hcom * hcom_d) / nH) / tau
        # free-free tail above the table (reference's else branch)
        lam_hi = 1.42e-27 * jnp.sqrt(tau) * 1.1
        lam = jnp.where(in_table, lam, lam_hi)
        lam_p = jnp.where(in_table, lam_p, lam_hi / (2.0 * tau))
        return lam, lam_p

    def cond(state):
        _tau, _tau_old, time, _time_old, active, it = state
        return jnp.logical_and(jnp.any(active), it < 500)

    def body(state):
        tau, tau_old, time, time_old, active, it = state
        lam, lam_p = rate(tau)
        wcool = jnp.maximum(jnp.maximum(jnp.abs(lam) / tau * VARMAX, wmax),
                            -lam_p * VARMAX)
        tau_new = tau * (1.0 + lam_p / wcool - lam / tau / wcool) \
            / (1.0 + lam_p / wcool)
        tau_old = jnp.where(active, tau, tau_old)
        tau = jnp.where(active, tau_new, tau)
        time_old = jnp.where(active, time, time_old)
        time = jnp.where(active, time + 1.0 / wcool, time)
        active = jnp.logical_and(active, time < time_max)
        return tau, tau_old, time, time_old, active, it + 1

    tau0 = T2
    state = (tau0, tau0, jnp.zeros_like(T2), jnp.zeros_like(T2),
             jnp.ones_like(T2, dtype=bool), jnp.array(0))
    tau, tau_old, time, time_old, _a, _it = jax.lax.while_loop(
        cond, body, state)

    # interpolate onto the exact end time (reference ':622-625')
    denom = jnp.where(time == time_old, 1.0, time - time_old)
    frac = jnp.clip((time_max - time_old) / denom, 0.0, 1.0)
    out = tau * frac + tau_old * (1.0 - frac)
    return out.reshape(shape)


# ----------------------------------------------------------------------
# per-step driver on a dense grid (cooling_fine equivalent)
# ----------------------------------------------------------------------

# ----------------------------------------------------------------------
# ISM cooling (Audit & Hennebelle 2005 — hydro/cooling_module_ism.f90)
# ----------------------------------------------------------------------

def _ism_rate(T, n):
    """Net heating-cooling rate [erg/s/cm^3] of the ISM module:
    fine-structure CII/OI + H Lyα + metastable lines + grain
    photoelectric heating + grain recombination below 10^4 K
    (``cooling_low``), Dopita & Sutherland piecewise fit above
    (``cooling_high``), blended at the module's 10035 K switch.
    Vectorized re-expression of the published formulas."""
    T = jnp.maximum(T, 1.0)
    n = jnp.maximum(n, 1e-10)
    kB = 1.38e-16

    # --- cooling_low (T < ~1e4 K) -----------------------------------
    ne = 2.4e-3 * (T / 100.0) ** 0.25 / 0.5      # Wolfire+03 C15
    x = jnp.clip(ne / n, 3.5e-4 * 0.4, 0.1)
    cold_cII = (92.0 * kB * 2.0
                * (2.8e-7 * (T / 100.0) ** -0.5 * x
                   + 8e-10 * (T / 100.0) ** 0.07)
                * 3.5e-4 * 0.4 * jnp.exp(-92.0 / T))
    cold_o = (1e-26 * jnp.sqrt(T)
              * (24.0 * jnp.exp(-228.0 / T)
                 + 7.0 * jnp.exp(-326.0 / T))) * 4.5e-4
    cold_h = 7.3e-19 * x * jnp.exp(-118400.0 / T)
    cold_cII_m = (6.2e4 * kB
                  * (2.3e-8 * (T / 1e4) ** -0.5 * x + 1e-12)
                  * jnp.exp(-6.2e4 / T) * 3.5e-4 * 0.4)
    lowT = T <= 1e4
    o1 = (2.3e4 * kB / 3.0
          * (5.1e-9 * (T / 1e4) ** jnp.where(lowT, 0.57, 0.17) * x
             + 1e-12) * jnp.exp(-2.3e4 / T))
    o2 = (4.9e4 * kB / 3.0
          * (2.5e-9 * (T / 1e4) ** jnp.where(lowT, 0.57, 0.13) * x
             + 1e-12) * jnp.exp(-4.9e4 / T))
    o3 = (2.6e4 * kB
          * (5.2e-9 * (T / 1e4) ** jnp.where(lowT, 0.57, 0.15) * x
             + 1e-12) * jnp.exp(-2.6e4 / T))
    cold_o_m = (o1 + o2 + o3) * 4.5e-4
    cold_lo = cold_cII + cold_h + cold_o + cold_o_m + cold_cII_m
    G0 = 1.0 / 1.7
    param = G0 * jnp.sqrt(T) / (n * x)
    eps_pe = (4.9e-2 / (1.0 + (param / 1925.0) ** 0.73)
              + 3.7e-2 * (T / 1e4) ** 0.7 / (1.0 + param / 5e3))
    hot = 1e-24 * eps_pe * G0
    bet = 0.74 / T ** 0.068
    cold_rec = 4.65e-30 * T ** 0.94 * param ** bet * x
    rate_lo = hot * n - n * n * (cold_lo + cold_rec)

    # --- cooling_high (Dopita & Sutherland piecewise log10 fit) ------
    logT = jnp.log10(T)
    c = jnp.where(
        logT < 4.0,
        0.1343 * logT ** 3 - 1.3906 * logT ** 2 + 5.1554 * logT
        - 31.967,
        jnp.where(
            logT < 4.25, 12.64 * logT - 75.56,
            jnp.where(
                logT < 4.35, -0.3 * logT - 20.565,
                jnp.where(
                    logT < 4.9, 1.745 * logT - 29.463,
                    jnp.where(
                        logT < 5.4, -20.9125,
                        jnp.where(
                            logT < 5.9, -1.795 * logT - 11.219,
                            jnp.where(
                                logT < 6.2, -21.8095,
                                jnp.where(logT < 6.7,
                                          -1.261 * logT - 13.991,
                                          -22.44))))))))
    rate_hi = -(n * n) * 10.0 ** c

    return jnp.where(T < 10035.0, rate_lo, rate_hi)


def solve_cooling_ism(nH, T2, dt_s, gamma: float = 5.0 / 3.0,
                      nsub: int = 200):
    """ISM thermal update: T2' such that the net Audit & Hennebelle
    rate integrates over ``dt_s`` seconds (``solve_cooling_ism`` /
    ``calc_temp``).  The reference's per-cell adaptive Newton loop
    becomes a fixed-substep semi-implicit iteration (vectorized, jit):
    each substep takes ΔT = R/(α/δt − dR/dT) with a 20% per-substep
    clamp — the same linearization, statically scheduled.  ``nsub``
    bounds the total relaxation: on the steep Dopita & Sutherland
    segments Newton advances ~T/29 per substep, so spanning 1e6 K →
    the cold branch needs O(200) substeps (the reference's unbounded
    adaptive inner loop does the equivalent work).

    ``T2`` is the reference's T/µ convention; the rate tables take the
    physical T ≈ T2·µ with the module's fixed µ≈1.4 (neutral ISM).
    """
    kB = 1.38e-16
    mu = 1.4
    alpha = nH * kB / (gamma - 1.0)          # per physical T
    dts = dt_s / nsub

    def body(i, T):
        eps = 1e-5
        r0 = _ism_rate(T, nH)
        r1 = _ism_rate(T * (1.0 + eps), nH)
        drdT = (r1 - r0) / (T * eps)
        # implicitness only where it DAMPS (dR/dT < 0): on segments
        # where cooling weakens with T the full Newton denominator
        # flips sign and would drive T the wrong way (the reference
        # avoids this by shrinking its adaptive inner dt; the 20%
        # clamp bounds the explicit branch instead)
        denom = alpha / dts + jnp.maximum(-drdT, 0.0)
        dT = r0 / denom
        dT = jnp.clip(dT, -0.2 * T, 0.2 * T)
        return jnp.maximum(T + dT, 3.0)

    T = jnp.maximum(T2 * mu, 3.0)
    T = jax.lax.fori_loop(0, nsub, body, T)
    return T / mu


@dataclass(frozen=True)
class CoolingSpec:
    """Static cooling configuration (from &COOLING_PARAMS)."""
    enabled: bool = False
    ism: bool = False            # Audit & Hennebelle module (cooling_ism)
    # ISM integrator substeps: 200 spans 1e6 K -> cold branch in one
    # call; runs whose per-step cooling is mild can lower it
    # (&COOLING_PARAMS ism_nsub)
    ism_nsub: int = 200
    metal: bool = False
    z_ave: float = 0.0           # mean metallicity when no metal tracer
    self_shielding: bool = False
    T2max: float = 1e50
    scale_T2: float = 1.0        # code (P/rho) → K
    scale_nH: float = 1.0        # code rho → H/cc
    scale_t: float = 1.0         # code time → s
    # polytrope temperature floor (barotropic_eos_* of &COOLING_PARAMS)
    floor_form: str = ""         # "" → no floor
    T2_eos: float = 10.0
    polytrope_rho_cu: float = 1.0  # break density [H/cc]
    polytrope_index: float = 1.0

    @classmethod
    def from_params(cls, p, units) -> "CoolingSpec":
        c = p.cooling
        raw_cool = (p.raw.get("cooling_params", {}) if p.raw else {})
        return cls(enabled=bool(c.cooling),
                   ism=bool(getattr(c, "cooling_ism", False)),
                   ism_nsub=int(raw_cool.get("ism_nsub", 200)),
                   metal=bool(c.metal),
                   z_ave=float(c.z_ave),
                   self_shielding=bool(c.self_shielding),
                   T2max=float(c.T2max),
                   scale_T2=units.scale_T2, scale_nH=units.scale_nH,
                   scale_t=units.scale_t,
                   floor_form=(str(c.barotropic_eos_form)
                               if c.barotropic_eos else ""),
                   T2_eos=float(c.T_eos),
                   polytrope_rho_cu=float(c.polytrope_rho)
                   / max(units.scale_d, 1e-300) * units.scale_nH
                   if c.polytrope_rho else 1.0,
                   polytrope_index=float(c.polytrope_index))


def cooling_step(u, tables: CoolingTables, spec: CoolingSpec, dt, cfg,
                 t2_floor=None, scales=None):
    """Apply cooling over dt (code units) to a dense conservative state
    ``u [nvar, *sp]`` — the vectorized ``cooling_fine`` pass: separate
    thermal from kinetic energy, convert to (nH, T2) in cgs, integrate,
    convert back.  ``t2_floor`` (same shape as rho, K) is the polytrope
    temperature subtracted before and re-added after (``:329-355``).

    ``scales``: optional traced [scale_T2, scale_nH, scale_t] overriding
    the static spec values — cosmological runs pass the CURRENT epoch's
    supercomoving conversions (units.f90 scales are aexp-dependent)
    without recompiling per epoch."""
    ndim = cfg.ndim
    if scales is None:
        s_T2, s_nH, s_t = spec.scale_T2, spec.scale_nH, spec.scale_t
    else:
        s_T2, s_nH, s_t = scales[0], scales[1], scales[2]
    rho = jnp.maximum(u[0], cfg.smallr)
    ekin = sum(0.5 * u[1 + d] ** 2 for d in range(ndim)) / rho
    eother = jnp.zeros_like(rho)
    for n in range(cfg.nener):
        eother = eother + u[ndim + 2 + n]
    eint = u[ndim + 1] - ekin - eother
    T2_code = (cfg.gamma - 1.0) * eint / rho
    T2 = T2_code * s_T2
    nH = rho * s_nH

    if t2_floor is None:
        if spec.floor_form:
            from ramses_tpu.hydro.eos import barotropic_eos_temperature
            t2_floor = barotropic_eos_temperature(
                nH, spec.floor_form, spec.T2_eos, spec.polytrope_rho_cu,
                spec.polytrope_index)
        else:
            t2_floor = jnp.zeros_like(T2)
    T2_excess = jnp.clip(T2 - t2_floor, T2_MIN, spec.T2max)

    boost = (jnp.maximum(jnp.exp(-nH / 0.01), 1e-20)
             if spec.self_shielding else jnp.ones_like(nH))
    zsolar = jnp.full_like(nH, spec.z_ave)

    if spec.ism:
        T2_new = solve_cooling_ism(nH, T2_excess, dt * s_t, cfg.gamma,
                                   nsub=spec.ism_nsub)
    else:
        T2_new = solve_cooling(tables, nH, T2_excess, zsolar, boost,
                               dt * s_t)
    T2_out = jnp.minimum(T2_new + t2_floor, spec.T2max)
    eint_new = T2_out / s_T2 * rho / (cfg.gamma - 1.0)
    return u.at[ndim + 1].set(eint_new + ekin + eother)
